"""SHEC — Shingled Erasure Code, trading storage for recovery efficiency.

Re-design of the reference `shec` plugin (/root/reference/src/erasure-code/
shec/ErasureCodeShec.{h,cc}): a (k, m, c) code whose parity rows are a
jerasure Vandermonde matrix with entries zeroed outside overlapping "shingle"
windows (shec_reedsolomon_coding_matrix), so each parity covers only a slice
of the data and single-chunk repair reads ~k*c/m chunks instead of k.
Tolerates any c erasures (not MDS for more).

- technique `multiple` (default) picks the (m1, c1)/(m2, c2) two-band split
  minimizing the reference's recovery-efficiency metric
  (shec_calc_recovery_efficiency1); `single` uses one band.
- Decode searches parity subsets for the smallest invertible recovery system
  (shec_make_decoding_matrix's minimum-dup search) and solves it with one
  bitsliced XOR-matmul; erased parities are re-encoded from recovered data.
- minimum_to_decode reports exactly the chunks that search reads.

Parameter envelope (ErasureCodeShec.cc:280-345): k<=12, k+m<=20, c<=m<=k;
defaults (k, m, c) = (4, 3, 2), w=8 (16/32 silently fall back like the
reference).
"""

from __future__ import annotations

import itertools
import threading
from typing import Mapping

import numpy as np

from ceph_tpu.gf import gf_invert_matrix, gf_matmul, jerasure_vandermonde_matrix

from .base import EINVAL, EIO, ErasureCode
from .interface import EcError, Profile
from .matrix_codec import PLAN_CACHE, MatrixCodecMixin

SINGLE = "single"
MULTIPLE = "multiple"


def _recovery_efficiency(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """shec_calc_recovery_efficiency1 (ErasureCodeShec.cc:424-463)."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [10**8] * k
    r_e1 = 0.0
    for band_m, band_c in ((m1, c1), (m2, c2)):
        for rr in range(band_m):
            start = (rr * k) // band_m % k
            end = ((rr + band_c) * k) // band_m % k
            width = ((rr + band_c) * k) // band_m - (rr * k) // band_m
            cc = start
            first = True
            while first or cc != end:
                first = False
                r_eff_k[cc] = min(r_eff_k[cc], width)
                cc = (cc + 1) % k
            r_e1 += width
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


def shec_coding_matrix(k: int, m: int, c: int, technique: str) -> np.ndarray:
    """(m, k) shingled coding rows (shec_reedsolomon_coding_matrix)."""
    if technique == SINGLE:
        m1, c1 = 0, 0
    else:
        best = None
        for c1_try in range(c // 2 + 1):
            for m1_try in range(m + 1):
                c2, m2 = c - c1_try, m - m1_try
                if m1_try < c1_try or m2 < c2:
                    continue
                if (m1_try == 0) != (c1_try == 0) or (m2 == 0) != (c2 == 0):
                    continue
                r = _recovery_efficiency(k, m1_try, m2, c1_try, c2)
                if best is None or r < best[0] - 1e-12:
                    best = (r, c1_try, m1_try)
        assert best is not None, "no valid shingle split"
        c1, m1 = best[1], best[2]
    m2, c2 = m - m1, c - c1
    coding = jerasure_vandermonde_matrix(k, m)[k:].copy()
    for band, (band_m, band_c, row_off) in enumerate(((m1, c1, 0), (m2, c2, m1))):
        for rr in range(band_m):
            end = (rr * k) // band_m % k
            start = ((rr + band_c) * k) // band_m % k
            cc = start
            while cc != end:
                coding[row_off + rr, cc] = 0
                cc = (cc + 1) % k
    return coding


class ErasureCodeShec(MatrixCodecMixin, ErasureCode):
    """Shingled erasure code; encode via the matrix mixin, custom decode."""

    def __init__(self, technique: str = MULTIPLE) -> None:
        super().__init__()
        if technique not in (SINGLE, MULTIPLE):
            raise EcError(EINVAL, f"technique={technique} must be single|multiple")
        self.technique = technique
        self.k = 0
        self.m = 0
        self.c = 0
        self.w = 8
        self._decode_search_cache: dict[tuple, tuple] = {}
        from ceph_tpu.common.lockdep import make_lock

        self._lock = make_lock("shec_decode_cache")

    # -- init ---------------------------------------------------------------

    def parse(self, profile: Profile) -> None:
        super().parse(profile)
        self.invalidate_matrix()
        self._decode_search_cache.clear()
        has = [key in profile and profile[key] for key in ("k", "m", "c")]
        if not any(has):
            self.k, self.m, self.c = 4, 3, 2
            profile.update({"k": "4", "m": "3", "c": "2"})
        elif not all(has):
            raise EcError(EINVAL, "(k, m, c) must all be chosen or none")
        else:
            self.k = self.to_int("k", profile, "4")
            self.m = self.to_int("m", profile, "3")
            self.c = self.to_int("c", profile, "2")
        k, m, c = self.k, self.m, self.c
        if k <= 0 or m <= 0 or c <= 0:
            raise EcError(EINVAL, f"(k, m, c)=({k}, {m}, {c}) must be positive")
        if m < c:
            raise EcError(EINVAL, f"c={c} must be <= m={m}")
        if k > 12:
            raise EcError(EINVAL, f"k={k} must be <= 12")
        if k + m > 20:
            raise EcError(EINVAL, f"k+m={k + m} must be <= 20")
        if k < m:
            raise EcError(EINVAL, f"m={m} must be <= k={k}")
        # w: the reference falls back to its default on any invalid value
        # (:355-371); our field core is GF(2^8), so every profile runs w=8.
        self.w = 8

    def init(self, profile: Profile) -> None:
        self.parse(profile)
        self.distribution_matrix()
        self._profile = dict(profile)

    def build_matrix(self) -> np.ndarray:
        coding = shec_coding_matrix(self.k, self.m, self.c, self.technique)
        return np.concatenate([np.eye(self.k, dtype=np.uint8), coding])

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    # -- decode search (shec_make_decoding_matrix semantics) ----------------

    def _search(self, want: tuple[int, ...], avails: tuple[int, ...]):
        """Find (rows, columns, inverse) for the smallest recovery system.

        rows: global chunk ids supplying the equations; columns: data chunk
        ids being solved; inverse: GF inverse of the system matrix.  Mirrors
        the reference's 2^m parity-subset scan with the min-dup/min-parity
        tie rules, and derives `minimum` the same way.
        """
        key = (want, avails)
        with self._lock:
            if key in self._decode_search_cache:
                return self._decode_search_cache[key]
        k, m = self.k, self.m
        matrix = self.distribution_matrix()[k:]
        want_x = list(want)
        # Wanting an erased parity drags in its data columns.
        for i in range(m):
            if want_x[k + i] and not avails[k + i]:
                for j in range(k):
                    if matrix[i, j]:
                        want_x[j] = 1
        best = None  # (dup, ek, rows, columns)
        minp = k + 1
        mindup = k + 1
        for parities in itertools.chain.from_iterable(
            itertools.combinations(range(m), n) for n in range(m + 1)
        ):
            ek = len(parities)
            if ek > minp:
                continue
            if not all(avails[k + p] for p in parities):
                continue
            rows = set()
            columns = set()
            for j in range(k):
                if want_x[j] and not avails[j]:
                    columns.add(j)
            for p in parities:
                rows.add(k + p)
                for j in range(k):
                    if matrix[p, j]:
                        columns.add(j)
                        if avails[j]:
                            rows.add(j)
            if len(rows) != len(columns):
                continue
            dup = len(rows)
            if dup == 0:
                best = (0, ek, [], [])
                mindup = 0
                break
            if dup < mindup:
                row_list = sorted(rows)
                col_list = sorted(columns)
                sysmat = np.zeros((dup, dup), dtype=np.uint8)
                for i, r in enumerate(row_list):
                    for j, col in enumerate(col_list):
                        if r < k:
                            sysmat[i, j] = 1 if r == col else 0
                        else:
                            sysmat[i, j] = matrix[r - k, col]
                inv = gf_invert_matrix(sysmat)
                if inv is None:
                    continue
                mindup = dup
                minp = ek
                best = (dup, ek, row_list, col_list, inv)
        if best is None or mindup == k + 1:
            result = None
        else:
            if best[0] == 0:
                rows_l, cols_l, inv = [], [], None
            else:
                rows_l, cols_l, inv = best[2], best[3], best[4]
            # minimum chunks (reference tail of shec_make_decoding_matrix).
            minimum = set(rows_l)
            for i in range(k):
                if want_x[i] and avails[i]:
                    minimum.add(i)
            for i in range(m):
                if want[k + i] and avails[k + i] and (k + i) not in minimum:
                    if any(matrix[i, j] and not want_x[j] for j in range(k)):
                        minimum.add(k + i)
            result = (rows_l, cols_l, inv, sorted(minimum))
        with self._lock:
            self._decode_search_cache[key] = result
        return result

    # -- interface overrides ------------------------------------------------

    def _minimum_to_decode(self, want_to_read: set[int], available: set[int]) -> set[int]:
        n = self.k + self.m
        if want_to_read <= available:
            return set(want_to_read)
        want = tuple(1 if i in want_to_read else 0 for i in range(n))
        avails = tuple(1 if i in available else 0 for i in range(n))
        res = self._search(want, avails)
        if res is None:
            raise EcError(EIO, f"cannot recover {want_to_read} from {available}")
        return set(res[3])

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        n = k + m
        avail_set = set(chunks)
        want = tuple(1 if i in want_to_read else 0 for i in range(n))
        avails = tuple(1 if i in avail_set else 0 for i in range(n))
        res = self._search(want, avails)
        if res is None:
            raise EcError(EIO, f"cannot recover {want_to_read} from {avail_set}")
        rows, cols, inv, _minimum = res
        if inv is not None and rows:
            sources = np.stack(
                [np.asarray(decoded[r], dtype=np.uint8) for r in rows]
            )
            # One bitsliced kernel launch solves the whole system; the
            # inverse is an operand, so any erasure pattern shares the
            # compiled kernel (matrix-as-data design).  Decode-time matrices
            # go through the bounded LRU, not the per-geometry encode cache.
            solved = np.asarray(PLAN_CACHE.lru_coder(inv)(sources))
            for i, col in enumerate(cols):
                if not avails[col]:
                    np.copyto(decoded[col], solved[i])
        # Re-encode erased parity from (now complete) data.
        matrix = self.distribution_matrix()[k:]
        erased_parity = [
            i for i in range(m) if want[k + i] and not avails[k + i]
        ]
        if erased_parity:
            data = np.stack(
                [np.asarray(decoded[j], dtype=np.uint8) for j in range(k)]
            )
            parity = gf_matmul(matrix[erased_parity], data)
            for idx, i in enumerate(erased_parity):
                np.copyto(decoded[k + i], parity[idx])
