"""Codec base class — mirror of `ErasureCode` (the default scaffolding).

Reference: /root/reference/src/erasure-code/ErasureCode.{h,cc}.  Provides the
shared machinery every codec inherits: chunk-size/padding contract
(encode_prepare, :150-185), default encode = prepare + encode_chunks
(:187-203), default decode = fill-missing + decode_chunks (:205-241),
first-k-available minimum_to_decode (:102-119), `mapping=` chunk remapping
(:260-279), and profile parsing helpers (:281-329).

TPU-first deltas from the reference:
- SIMD_ALIGN=32 (ErasureCode.cc:42) generalizes to `ALIGNMENT`, default 128 —
  the TPU lane width — so chunk buffers always tile cleanly onto the VPU/MXU
  lane dimension.  get_chunk_size keeps the exact pad-up contract of
  ErasureCodeIsa.cc:65-79.
- Buffers are numpy uint8 arrays; the zero-fill that `encode_prepare` does
  with aligned bufferptrs becomes plain array padding.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from .interface import EcError, ErasureCodeInterface, Profile

from ..common.errs import EINVAL, EIO, ENOENT  # noqa: F401 (historic home)


class ErasureCode(ErasureCodeInterface):
    # TPU lane width; the reference's SIMD_ALIGN=32 analog.
    ALIGNMENT = 128

    def __init__(self) -> None:
        self._profile: Profile = {}
        self.chunk_mapping: list[int] = []

    # -- profile helpers (ErasureCode.cc:281-329) ---------------------------

    @staticmethod
    def to_int(name: str, profile: Profile, default: str) -> int:
        if not profile.get(name):
            profile[name] = default
        try:
            return int(profile[name])
        except ValueError as e:
            raise EcError(EINVAL, f"could not convert {name}={profile[name]} to int") from e

    @staticmethod
    def to_bool(name: str, profile: Profile, default: str) -> bool:
        if not profile.get(name):
            profile[name] = default
        return profile[name] in ("yes", "true")

    @staticmethod
    def to_string(name: str, profile: Profile, default: str) -> str:
        if not profile.get(name):
            profile[name] = default
        return profile[name]

    @staticmethod
    def sanity_check_k_m(k: int, m: int) -> None:
        """ErasureCode.cc:84-95."""
        if k < 2:
            raise EcError(EINVAL, f"k={k} must be >= 2")
        if m < 1:
            raise EcError(EINVAL, f"m={m} must be >= 1")

    # -- init / profile -----------------------------------------------------

    def init(self, profile: Profile) -> None:
        self.parse(profile)
        # Own copy, like the reference's by-value profile member — makes the
        # registry's round-trip check meaningful (ErasureCodePlugin.cc:108-113).
        self._profile = dict(profile)

    def parse(self, profile: Profile) -> None:
        """Base parse: chunk remapping via `mapping=` (ErasureCode.cc:260-279).

        The mapping string has one char per chunk position; 'D' positions take
        data chunks in order, the rest take coding chunks in order.
        """
        mapping = profile.get("mapping")
        if mapping:
            data_pos = [i for i, c in enumerate(mapping) if c == "D"]
            coding_pos = [i for i, c in enumerate(mapping) if c != "D"]
            self.chunk_mapping = data_pos + coding_pos

    def get_profile(self) -> Profile:
        return self._profile

    def get_chunk_mapping(self) -> list[int]:
        return self.chunk_mapping

    def chunk_index(self, i: int) -> int:
        """ErasureCode.cc:97-100."""
        return self.chunk_mapping[i] if len(self.chunk_mapping) > i else i

    # -- geometry -----------------------------------------------------------

    def get_alignment(self) -> int:
        return self.ALIGNMENT

    def get_chunk_size(self, object_size: int) -> int:
        """ceil(object/k) padded up to alignment (ErasureCodeIsa.cc:65-79)."""
        k = self.get_data_chunk_count()
        chunk_size = (object_size + k - 1) // k
        align = self.get_alignment()
        modulo = chunk_size % align
        if modulo:
            chunk_size += align - modulo
        return chunk_size

    # -- minimum_to_decode (ErasureCode.cc:102-148) -------------------------

    def _minimum_to_decode(self, want_to_read: set[int], available: set[int]) -> set[int]:
        if want_to_read <= available:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available) < k:
            raise EcError(EIO, f"need {k} chunks, only {len(available)} available")
        return set(sorted(available)[:k])

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int]
    ) -> dict[int, list[tuple[int, int]]]:
        shards = self._minimum_to_decode(want_to_read, available)
        sub = [(0, self.get_sub_chunk_count())]
        return {s: list(sub) for s in sorted(shards)}

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: Mapping[int, int]
    ) -> set[int]:
        return self._minimum_to_decode(want_to_read, set(available))

    # -- encode path (ErasureCode.cc:150-203) -------------------------------

    def encode_prepare(self, raw: np.ndarray) -> dict[int, np.ndarray]:
        """Pad/split an object into k aligned data chunks + m zeroed parity
        buffers, honoring chunk_index remapping (ErasureCode.cc:150-185)."""
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        blocksize = self.get_chunk_size(raw.size)
        padded = np.zeros(k * blocksize, dtype=np.uint8)
        padded[: raw.size] = raw
        chunks: dict[int, np.ndarray] = {}
        for i in range(k):
            chunks[self.chunk_index(i)] = padded[i * blocksize : (i + 1) * blocksize]
        for i in range(k, k + m):
            chunks[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        return chunks

    def encode(self, want_to_encode: set[int], data: bytes | np.ndarray) -> dict[int, np.ndarray]:
        raw = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.asarray(data, dtype=np.uint8).ravel()
        chunks = self.encode_prepare(raw)
        self.encode_chunks(chunks)
        # Out-of-range ids in want_to_encode are filtered, like the
        # reference's erase-non-wanted loop (ErasureCode.cc:198-201).
        return {i: chunks[i] for i in want_to_encode if i in chunks}

    # -- decode path (ErasureCode.cc:205-248) -------------------------------

    def _decode(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray]
    ) -> dict[int, np.ndarray]:
        have = set(chunks)
        if want_to_read <= have:
            return {i: np.asarray(chunks[i]) for i in want_to_read}
        if not chunks:
            raise EcError(EIO, "no chunks available to decode from")
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        blocksize = len(next(iter(chunks.values())))
        decoded: dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i in chunks:
                decoded[i] = np.asarray(chunks[i], dtype=np.uint8)
            else:
                decoded[i] = np.zeros(blocksize, dtype=np.uint8)
        self.decode_chunks(want_to_read, chunks, decoded)
        return {i: decoded[i] for i in want_to_read}

    def decode(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        chunk_size: int = 0,
    ) -> dict[int, np.ndarray]:
        return self._decode(want_to_read, chunks)

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> np.ndarray:
        """ErasureCode.cc:331-347."""
        k = self.get_data_chunk_count()
        want = {self.chunk_index(i) for i in range(k)}
        decoded = self._decode(want, chunks)
        return np.concatenate([decoded[self.chunk_index(i)] for i in range(k)])
