"""Plugin registry — mirror of `ErasureCodePluginRegistry`.

Reference: /root/reference/src/erasure-code/ErasureCodePlugin.{h,cc}.  The
reference dlopens `libec_<name>.so`, checks `__erasure_code_version()` against
the build version (mismatch -> -EXDEV, :134-143), calls
`__erasure_code_init(name, dir)` which registers a Plugin whose `factory()`
builds codec instances, and verifies the instance's profile round-trips
(:86-114).

Here plugins are Python modules under `ceph_tpu.codec.plugins` loaded on
demand (the import system plays dlopen's role); each must expose a module-level
`__erasure_code_version__` string and an `__erasure_code_init__(registry)`
entry point.  The native shell (native/) re-exports this registry behind the
exact C ABI so a real Ceph OSD can dlopen `libec_tpu.so`.
"""

from __future__ import annotations

import importlib
import threading
from typing import Callable

from ceph_tpu.common.lockdep import make_lock, make_rlock

from .interface import EcError, ErasureCodeInterface, Profile

# The ABI version plugins must declare (reference: CEPH_GIT_NICE_VER check).
EC_VERSION = "ceph_tpu-1"

EXDEV = 18
ENOENT = 2
EEXIST = 17

PLUGIN_PACKAGE = "ceph_tpu.codec.plugins"


class ErasureCodePlugin:
    """A registered factory (ErasureCodePlugin.h:39)."""

    def __init__(self, name: str, factory: Callable[[Profile], ErasureCodeInterface]):
        self.name = name
        self._factory = factory

    def factory(self, profile: Profile) -> ErasureCodeInterface:
        ec = self._factory(profile)
        return ec


class ErasureCodePluginRegistry:
    """Singleton get-or-load registry (ErasureCodePlugin.h:45)."""

    _instance: "ErasureCodePluginRegistry | None" = None
    _instance_lock = make_lock("codec_registry_instance")

    def __init__(self) -> None:
        self._lock = make_rlock("codec_registry")
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self.disable_dlclose = False  # kept for harness parity (bench sets it)

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        """ErasureCodePlugin.cc registry.add: duplicate -> -EEXIST."""
        with self._lock:
            if name in self._plugins:
                raise EcError(EEXIST, f"plugin {name} already registered")
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        with self._lock:
            return self._plugins.get(name)

    def load(self, name: str) -> ErasureCodePlugin:
        """Import-and-register, with the reference's failure-mode contract:
        missing entry point / bad version map to the same errnos the dlopen
        path produces (ErasureCodePlugin.cc:126-163)."""
        with self._lock:
            plugin = self._plugins.get(name)
            if plugin is not None:
                return plugin
            try:
                mod = importlib.import_module(f"{PLUGIN_PACKAGE}.{name}")
            except ImportError as e:
                raise EcError(ENOENT, f"plugin {name} not found") from e
            version = getattr(mod, "__erasure_code_version__", None)
            if version is None:
                raise EcError(EXDEV, f"plugin {name} missing __erasure_code_version__")
            if version != EC_VERSION:
                raise EcError(
                    EXDEV, f"plugin {name} version {version} != expected {EC_VERSION}"
                )
            init = getattr(mod, "__erasure_code_init__", None)
            if init is None:
                raise EcError(ENOENT, f"plugin {name} missing __erasure_code_init__")
            init(self)
            plugin = self._plugins.get(name)
            if plugin is None:
                raise EcError(EXDEV, f"plugin {name} init did not register itself")
            return plugin

    def factory(self, name: str, profile: Profile) -> ErasureCodeInterface:
        """Get-or-load + instantiate + profile round-trip check
        (ErasureCodePlugin.cc:86-114)."""
        plugin = self.load(name)
        ec = plugin.factory(profile)
        got = ec.get_profile()
        if got != profile:
            raise EcError(
                EXDEV,
                f"profile {profile} != get_profile() {got} for plugin {name}",
            )
        return ec

    def preload(self, plugins_list: str) -> None:
        """Load a comma- or space-separated plugin list at startup
        (ErasureCodePlugin.cc:180-196; used by OSD boot via
        osd_erasure_code_plugins)."""
        for name in plugins_list.replace(",", " ").split():
            if name:
                self.load(name)


def instance() -> ErasureCodePluginRegistry:
    return ErasureCodePluginRegistry.instance()


# -- native plugin dlopen path (ErasureCodePlugin.cc:126-163) -----------------

# The C-ABI version native plugins must export (the reference checks
# CEPH_GIT_NICE_VER; ours is the native ABI string in native/ec_native.cc).
EC_NATIVE_ABI_VERSION = "ceph-tpu-ec-1.0"


def load_dynamic(name: str, directory: str):
    """dlopen `libec_<name>.so` with the reference's contract:

    - load with RTLD_NOW (ErasureCodePlugin.cc:126-128);
    - missing `__erasure_code_version` or a mismatch -> -EXDEV (:134-143);
    - missing `__erasure_code_init` -> -ENOENT; nonzero init return
      propagates (:145-163).

    Returns the loaded CDLL with the region-engine symbols typed."""
    import ctypes
    import os

    path = os.path.join(directory, f"libec_{name}.so")
    if not os.path.exists(path):
        raise EcError(ENOENT, f"plugin library {path} not found")
    try:
        lib = ctypes.CDLL(path, mode=ctypes.RTLD_LOCAL | os.RTLD_NOW)
    except OSError as e:
        raise EcError(EXDEV, f"dlopen {path} failed: {e}") from e
    try:
        version_fn = lib.__erasure_code_version
    except AttributeError as e:
        raise EcError(EXDEV, f"{path} missing __erasure_code_version") from e
    version_fn.restype = ctypes.c_char_p
    version = version_fn().decode()
    if version != EC_NATIVE_ABI_VERSION:
        raise EcError(
            EXDEV, f"{path} version {version!r} != expected {EC_NATIVE_ABI_VERSION!r}"
        )
    try:
        init_fn = lib.__erasure_code_init
    except AttributeError as e:
        raise EcError(ENOENT, f"{path} missing __erasure_code_init") from e
    init_fn.restype = ctypes.c_int
    init_fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    rc = init_fn(name.encode(), directory.encode())
    if rc != 0:
        raise EcError(abs(rc) or EXDEV, f"{path} init failed ({rc})")
    # type the region-engine surface (plugins beyond the entry points)
    for sym, restype, argtypes in [
        ("ec_tables_new", ctypes.c_void_p,
         [ctypes.c_int, ctypes.c_int, ctypes.c_char_p]),
        ("ec_tables_apply", None,
         [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]),
        ("ec_tables_free", None, [ctypes.c_void_p]),
        ("ec_gf_invert_matrix", ctypes.c_int,
         [ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int]),
        ("ec_region_xor", None,
         [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p, ctypes.c_size_t]),
    ]:
        fn = getattr(lib, sym, None)
        if fn is not None:
            fn.restype = restype
            fn.argtypes = argtypes
    return lib
