"""LRC — Locally Repairable Codes as layered composition of inner codecs.

Re-design of the reference `lrc` plugin (/root/reference/src/erasure-code/
lrc/ErasureCodeLrc.{h,cc}): a profile is either a JSON `layers` array plus a
global `mapping` string, or the k/m/l shorthand expanded by parse_kml
(ErasureCodeLrc.cc:290-393).  Each layer holds its own inner codec (default
jerasure reed_sol_van, layers_init :210-247) over a subset of the global
chunk positions given by its chunks_map ('D' data, 'c' coding, '_' absent).

Encode runs layers top-down with global<->layer index swaps
(encode_chunks :?); decode walks layers in reverse, each layer repairing the
erasures it can cover, gradually improving `decoded` (decode_chunks);
_minimum_to_decode prefers the smallest covering layer so local repairs read
fewer shards — the locality property that makes LRC worth its extra parity.

On TPU every inner layer is a matrix codec riding the shared bitsliced
XOR-matmul kernels, so a local repair is one small kernel launch over the
layer's chunk subset.
"""

from __future__ import annotations

import json
import re
from typing import Mapping

import numpy as np

from .base import EINVAL, EIO, ErasureCode
from .interface import EcError, ErasureCodeInterface, Profile

# The reference's dedicated error codes (ErasureCodeLrc.h:25-45) map to
# EINVAL at this surface; messages carry the distinction.
DEFAULT_KML = "-1"


class Layer:
    """One coding layer (ErasureCodeLrc.h:51-61)."""

    def __init__(self, chunks_map: str, profile: Profile):
        self.chunks_map = chunks_map
        self.profile = profile
        self.data = [i for i, c in enumerate(chunks_map) if c == "D"]
        self.coding = [i for i, c in enumerate(chunks_map) if c == "c"]
        self.chunks = self.data + self.coding
        self.chunks_as_set = set(self.chunks)
        self.erasure_code: ErasureCodeInterface | None = None


def _parse_layer_profile(spec) -> Profile:
    """Second layer element: "", "k=v k=v", or a JSON object."""
    if isinstance(spec, dict):
        return {str(k): str(v) for k, v in spec.items()}
    spec = spec.strip()
    if not spec:
        return {}
    if spec.startswith("{"):
        return {str(k): str(v) for k, v in json.loads(spec).items()}
    out: Profile = {}
    for token in spec.split():
        if "=" not in token:
            raise EcError(EINVAL, f"layer profile token {token!r} is not k=v")
        key, val = token.split("=", 1)
        out[key] = val
    return out


def _lenient_json(text: str):
    """json_spirit accepts trailing commas (the kml generator emits them)."""
    cleaned = re.sub(r",(\s*[\]}])", r"\1", text)
    try:
        return json.loads(cleaned)
    except json.JSONDecodeError as e:
        raise EcError(EINVAL, f"could not parse layers JSON: {e}") from e


class ErasureCodeLrc(ErasureCode):
    """Layered locally-repairable code."""

    def __init__(self) -> None:
        super().__init__()
        self.layers: list[Layer] = []
        self._chunk_count = 0
        self._data_chunk_count = 0

    # -- profile parsing ----------------------------------------------------

    def parse_kml(self, profile: Profile) -> None:
        """Expand k/m/l shorthand into mapping + layers
        (ErasureCodeLrc.cc:290-393)."""
        k = self.to_int("k", profile, DEFAULT_KML)
        m = self.to_int("m", profile, DEFAULT_KML)
        lr = self.to_int("l", profile, DEFAULT_KML)
        if k == -1 and m == -1 and lr == -1:
            return
        if -1 in (k, m, lr):
            raise EcError(EINVAL, "all of k, m, l must be set or none of them")
        for generated in ("mapping", "layers", "crush-steps"):
            if generated in profile:
                raise EcError(
                    EINVAL, f"the {generated} parameter cannot be set with k/m/l"
                )
        if lr == 0 or (k + m) % lr:
            raise EcError(EINVAL, "k + m must be a multiple of l")
        groups = (k + m) // lr
        if k % groups:
            raise EcError(EINVAL, "k must be a multiple of (k + m) / l")
        if m % groups:
            raise EcError(EINVAL, "m must be a multiple of (k + m) / l")
        kg, mg = k // groups, m // groups
        profile["mapping"] = ("D" * kg + "_" * mg + "_") * groups
        layers = "[ "
        layers += ' [ "' + ("D" * kg + "c" * mg + "_") * groups + '", "" ],'
        for i in range(groups):
            layers += ' [ "'
            for j in range(groups):
                layers += ("D" * lr + "c") if i == j else ("_" * (lr + 1))
            layers += '", "" ],'
        profile["layers"] = layers + "]"

    def _layers_parse(self, description_string: str) -> None:
        description = _lenient_json(description_string)
        if not isinstance(description, list):
            raise EcError(EINVAL, "layers must be a JSON array")
        for position, entry in enumerate(description):
            if not isinstance(entry, list):
                raise EcError(
                    EINVAL, f"layers[{position}] must be a JSON array, got {entry!r}"
                )
            if not entry or not isinstance(entry[0], str):
                raise EcError(
                    EINVAL, f"layers[{position}][0] must be the chunks_map string"
                )
            layer_profile = _parse_layer_profile(entry[1]) if len(entry) > 1 else {}
            self.layers.append(Layer(entry[0], layer_profile))

    def _layers_init(self) -> None:
        """Instantiate inner codecs (ErasureCodeLrc.cc:210-247)."""
        from . import registry as registry_mod

        registry = registry_mod.instance()
        for layer in self.layers:
            prof = layer.profile
            prof.setdefault("k", str(len(layer.data)))
            prof.setdefault("m", str(len(layer.coding)))
            prof.setdefault("plugin", "jerasure")
            prof.setdefault("technique", "reed_sol_van")
            plugin = prof["plugin"]
            layer.erasure_code = registry.factory(plugin, prof)

    def _layers_sanity_checks(self) -> None:
        if len(self.layers) < 1:
            raise EcError(EINVAL, "layers parameter needs at least one layer")
        for position, layer in enumerate(self.layers):
            if len(layer.chunks_map) != self._chunk_count:
                raise EcError(
                    EINVAL,
                    f"layers[{position}] map {layer.chunks_map!r} must be "
                    f"{self._chunk_count} characters long",
                )

    def init(self, profile: Profile) -> None:
        self.parse_kml(profile)
        self.parse(profile)  # base: chunk_mapping from `mapping`
        if "layers" not in profile:
            raise EcError(EINVAL, "could not find 'layers' in profile")
        description_string = profile["layers"]
        self._layers_parse(description_string)
        self._layers_init()
        if "mapping" not in profile:
            raise EcError(EINVAL, "the 'mapping' profile is missing")
        mapping = profile["mapping"]
        self._data_chunk_count = mapping.count("D")
        self._chunk_count = len(mapping)
        self._layers_sanity_checks()
        # kml-generated parameters are not exposed (ErasureCodeLrc.cc:539-543).
        if profile.get("l", DEFAULT_KML) != DEFAULT_KML:
            profile.pop("mapping", None)
            profile.pop("layers", None)
        self._profile = dict(profile)

    # -- geometry -----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self._chunk_count

    def get_data_chunk_count(self) -> int:
        return self._data_chunk_count

    def get_chunk_size(self, object_size: int) -> int:
        """Delegates to the first (global) layer (ErasureCodeLrc.cc)."""
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    # -- minimum_to_decode (locality-aware; ErasureCodeLrc.cc cases 1-3) ----

    def _minimum_to_decode(self, want_to_read: set[int], available: set[int]) -> set[int]:
        erasures_total = {
            i for i in range(self.get_chunk_count()) if i not in available
        }
        erasures_not_recovered = set(erasures_total)
        erasures_want = erasures_total & want_to_read

        # Case 1: nothing wanted is missing.
        if not erasures_want:
            return set(want_to_read)

        # Case 2: walk layers from most local (last) to global, taking the
        # smallest layer that can repair each wanted erasure.
        minimum: set[int] = set()
        for layer in reversed(self.layers):
            layer_want = want_to_read & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures_want = layer_want & erasures_want
            if not layer_erasures_want:
                minimum |= layer_want
                continue
            erasures = layer.chunks_as_set & erasures_not_recovered
            if len(erasures) > layer.erasure_code.get_coding_chunk_count():
                continue  # too many for this layer; hope an upper layer helps
            minimum |= layer.chunks_as_set - erasures_not_recovered
            erasures_not_recovered -= erasures
            erasures_want -= erasures
        if not erasures_want:
            minimum |= want_to_read
            minimum -= erasures_total
            return minimum

        # Case 3: repair everything repairable anywhere; if that clears all
        # erasures, read all available chunks.
        erasures_total = {
            i for i in range(self.get_chunk_count()) if i not in available
        }
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures_total
            if not layer_erasures:
                continue
            if len(layer_erasures) <= layer.erasure_code.get_coding_chunk_count():
                erasures_total -= layer_erasures
        if not erasures_total:
            return set(available)

        raise EcError(EIO, f"not enough chunks in {available} to read {want_to_read}")

    # -- encode / decode ----------------------------------------------------

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        """Apply layers top-down with global<->layer index swap."""
        want = set(chunks)
        top = len(self.layers)
        for idx in range(len(self.layers) - 1, -1, -1):
            top = idx
            if want <= self.layers[idx].chunks_as_set:
                break
        for layer in self.layers[top:]:
            layer_chunks = {j: chunks[c] for j, c in enumerate(layer.chunks)}
            layer.erasure_code.encode_chunks(layer_chunks)
            for j, c in enumerate(layer.chunks):
                chunks[c] = layer_chunks[j]

    def decode_chunks(
        self,
        want_to_read: set[int],
        chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        """Reverse-layer repair, gradually improving `decoded`.

        The reference makes a single reverse pass (ErasureCodeLrc.cc
        decode_chunks), which misses cascades where a global repair unlocks a
        later local repair (e.g. kml(4,2,3) losing a data chunk and its own
        local parity).  Its _minimum_to_decode case 3 nevertheless promises
        such cascades, so we iterate passes until the wanted chunks are
        recovered or a pass makes no progress — a strict superset of the
        reference's recoverability.
        """
        erasures = {i for i in range(self.get_chunk_count()) if i not in chunks}
        want_to_read_erasures = erasures & want_to_read
        progress = True
        while want_to_read_erasures and progress:
            progress = False
            for layer in reversed(self.layers):
                layer_erasures = layer.chunks_as_set & erasures
                if not layer_erasures:
                    continue
                if len(layer_erasures) > layer.erasure_code.get_coding_chunk_count():
                    continue  # too many for this layer
                layer_want: set[int] = set()
                layer_chunks: dict[int, np.ndarray] = {}
                layer_decoded: dict[int, np.ndarray] = {}
                for j, c in enumerate(layer.chunks):
                    # Pick from `decoded` (not `chunks`) to reuse chunks
                    # repaired by previous layers/passes.
                    if c not in erasures:
                        layer_chunks[j] = decoded[c]
                    if c in want_to_read:
                        layer_want.add(j)
                    layer_decoded[j] = decoded[c]
                layer.erasure_code.decode_chunks(
                    layer_want, layer_chunks, layer_decoded
                )
                for j, c in enumerate(layer.chunks):
                    decoded[c] = layer_decoded[j]
                    erasures.discard(c)
                progress = True
                want_to_read_erasures = erasures & want_to_read
                if not want_to_read_erasures:
                    break
        if want_to_read_erasures:
            raise EcError(
                EIO, f"unable to read {want_to_read_erasures} of {want_to_read}"
            )
