"""ceph-bluestore-tool analog — offline BlueStore maintenance.

Mirror of src/os/bluestore's fsck surface (BlueStore::_fsck; the
reference exposes it through `ceph-bluestore-tool fsck --path ...`):

    python -m ceph_tpu.tools.bluestore_tool --path DIR --op fsck [--deep]
    python -m ceph_tpu.tools.bluestore_tool --path DIR --op show-label

fsck checks, offline and read-only:
- every onode extent's crc32c against the stored block bytes (deep; the
  shallow pass checks structure only, as the reference splits
  fsck/deep-fsck)
- no physical block referenced by two onodes (extent overlap — the
  reference's shared-blob accounting violation)
- every referenced block is within the device and marked used by the
  rebuilt allocator
- pending WAL records decode (a torn deferred write is reported, not
  replayed)

Exit status 0 = consistent, 1 = errors found (count on stdout).
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import sys

from ..os.bluestore import BLOCK, BlueStore, Onode, _ONODE, _WAL
from ..utils.crc32c import crc32c


def op_fsck(path: str, deep: bool) -> int:
    store = BlueStore(path)
    store.mount()
    errors: list[str] = []
    owners: dict[int, str] = {}  # physical block -> "coll/oid"
    device_blocks = os.path.getsize(os.path.join(path, "block")) // BLOCK
    n_onodes = 0
    for key, blob in store.db.iterate(_ONODE):
        n_onodes += 1
        coll, _, oid = key.partition("\x00")
        who = f"{coll}/{oid}"
        try:
            o = Onode.decode(blob)
        except Exception as e:
            errors.append(f"onode {who}: undecodable ({e})")
            continue
        for bidx, (poff, crc, clen) in o.blocks.items():
            blk = poff // BLOCK
            if poff % BLOCK or blk >= device_blocks:
                errors.append(
                    f"onode {who} block {bidx}: bad extent poff={poff}"
                )
                continue
            prev = owners.get(blk)
            if prev is not None and prev != who:
                errors.append(
                    f"block {blk}: referenced by BOTH {prev} and {who}"
                )
            owners[blk] = who
            if deep:
                stored = store._block_read(poff, clen or BLOCK)
                if crc32c(stored) != crc:
                    errors.append(
                        f"onode {who} block {bidx}: csum mismatch "
                        f"(stored@{poff})"
                    )
    n_wal = 0
    for key, val in store.db.iterate(_WAL):
        n_wal += 1
        if len(val) < 8 + 1:
            errors.append(f"wal {key}: truncated record")
            continue
        (poff,) = struct.unpack_from("<Q", val)
        if poff % BLOCK or poff // BLOCK >= device_blocks:
            errors.append(f"wal {key}: bad target poff={poff}")
    store.umount()
    print(
        f"fsck {'deep ' if deep else ''}scanned {n_onodes} onodes, "
        f"{len(owners)} extents, {n_wal} pending wal records: "
        f"{len(errors)} error(s)"
    )
    for e in errors:
        print(f"  {e}")
    return 1 if errors else 0


def op_show_label(path: str) -> int:
    """Superblock-ish summary (the reference's show-label JSON)."""
    store = BlueStore(path)
    store.mount()
    label = {
        "path": path,
        "size": os.path.getsize(os.path.join(path, "block")),
        "block_size": BLOCK,
        "collections": sorted(store._colls),
        "objects": sum(store._obj_count.values()),
        "free_blocks": store.alloc.num_free(),
    }
    store.umount()
    print(json.dumps(label, indent=2))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--path", required=True)
    p.add_argument("--op", required=True, choices=["fsck", "show-label"])
    p.add_argument("--deep", action="store_true")
    args = p.parse_args(argv)
    if args.op == "fsck":
        return op_fsck(args.path, args.deep)
    return op_show_label(args.path)


if __name__ == "__main__":
    sys.exit(main())
