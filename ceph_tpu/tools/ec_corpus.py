"""Encode/decode non-regression corpus tool.

Mirror of /root/reference/src/test/erasure-code/ceph_erasure_code_non_regression.cc
(driver: qa/workunits/erasure-code/encode-decode-non-regression.sh): `--create`
writes a content file plus the per-chunk encodings of it into a directory
named after the profile; `--check` re-encodes the stored content and fails if
any chunk byte differs, then decodes one- and two-erasure cases and fails if
any chunk is incorrectly recovered.  A checked-in corpus therefore pins
today's chunk bytes: any future change to matrix math, padding, or kernel
layout that alters even one byte fails the suite.

Unlike the reference (rand()-seeded payload), the payload is deterministic so
`--create` is reproducible byte-for-byte from a clean checkout.

Usage:
  python -m ceph_tpu.tools.ec_corpus --create --base DIR --plugin tpu \
      --stripe-width 4096 -P k=8 -P m=3
  python -m ceph_tpu.tools.ec_corpus --check  --base DIR --plugin tpu \
      --stripe-width 4096 -P k=8 -P m=3
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from ceph_tpu.codec import registry as registry_mod
from ceph_tpu.codec.interface import EcError, Profile

PAYLOAD_CHUNK = 37  # reference payload repeat unit


def payload_bytes(stripe_width: int) -> bytes:
    """Deterministic 'a'..'z' pattern (the reference fills 37-byte units
    with rand() letters; determinism matters more than randomness here)."""
    unit = bytes(ord("a") + (11 * j + 5) % 26 for j in range(PAYLOAD_CHUNK))
    reps = stripe_width // PAYLOAD_CHUNK + 1
    return (unit * reps)[:stripe_width]


def corpus_dir(base: str, plugin: str, stripe_width: int, profile: Profile) -> str:
    name = f"plugin={plugin} stripe-width={stripe_width}"
    for key in sorted(profile):
        name += f" {key}={profile[key]}"
    return os.path.join(base, name)


def _factory(plugin: str, profile: Profile):
    return registry_mod.instance().factory(plugin, dict(profile))


def create(base: str, plugin: str, stripe_width: int, profile: Profile) -> int:
    ec = _factory(plugin, profile)
    directory = corpus_dir(base, plugin, stripe_width, profile)
    os.makedirs(directory, exist_ok=True)
    content = payload_bytes(stripe_width)
    with open(os.path.join(directory, "content"), "wb") as f:
        f.write(content)
    n = ec.get_chunk_count()
    encoded = ec.encode(set(range(n)), content)
    for i, chunk in encoded.items():
        with open(os.path.join(directory, f"chunk.{i}"), "wb") as f:
            f.write(np.asarray(chunk, dtype=np.uint8).tobytes())
    print(f"created {directory}")
    return 0


def _decode_erasures(ec, erasures: set[int], encoded: dict[int, np.ndarray]) -> int:
    available = {i: c for i, c in encoded.items() if i not in erasures}
    chunk_size = len(next(iter(available.values())))
    decoded = ec.decode(set(erasures), available, chunk_size)
    for e in erasures:
        if not np.array_equal(decoded[e], encoded[e]):
            print(f"chunk {e} incorrectly recovered", file=sys.stderr)
            return 1
    return 0


def check(base: str, plugin: str, stripe_width: int, profile: Profile) -> int:
    ec = _factory(plugin, profile)
    directory = corpus_dir(base, plugin, stripe_width, profile)
    with open(os.path.join(directory, "content"), "rb") as f:
        content = f.read()
    n = ec.get_chunk_count()
    encoded = ec.encode(set(range(n)), content)
    for i in range(n):
        with open(os.path.join(directory, f"chunk.{i}"), "rb") as f:
            existing = f.read()
        now = np.asarray(encoded[i], dtype=np.uint8).tobytes()
        if existing != now:
            print(f"chunk {i} encodes differently", file=sys.stderr)
            return 1
    # single erasure: the fast/special path in most plugins
    if rc := _decode_erasures(ec, {0}, encoded):
        return rc
    if n - ec.get_data_chunk_count() > 1:
        # two erasures: the general decode path
        if rc := _decode_erasures(ec, {0, n - 1}, encoded):
            return rc
    return 0


# The standing corpus configurations: the five BASELINE.md configs plus one
# per additional implemented technique family.
STANDARD_CONFIGS: list[tuple[str, int, dict[str, str]]] = [
    ("jerasure", 4096, {"k": "4", "m": "2", "technique": "reed_sol_van"}),
    ("tpu", 4096, {"k": "8", "m": "3", "technique": "cauchy"}),
    ("tpu", 4096, {"k": "10", "m": "4", "technique": "reed_sol_van"}),
    ("clay", 8192, {"k": "8", "m": "4", "d": "11"}),
    # BASELINE.md names LRC(10,4,l=5), but the reference's own parse_kml
    # constraints ((k+m) % l == 0 and k % ((k+m)/l) == 0, ErasureCodeLrc.cc)
    # rule that shape out; the nearest valid shape keeping l=5 is (12,3,5).
    ("lrc", 4096, {"k": "12", "m": "3", "l": "5"}),
    ("jerasure", 4096, {"k": "5", "m": "2", "technique": "liberation",
                        "w": "5", "packetsize": "32"}),
    ("jerasure", 4096, {"k": "4", "m": "2", "technique": "blaum_roth",
                        "w": "6", "packetsize": "32"}),
    ("jerasure", 4096, {"k": "6", "m": "2", "technique": "liber8tion",
                        "packetsize": "32"}),
    ("shec", 4096, {"k": "4", "m": "3", "c": "2"}),
    ("xor", 4096, {"k": "4"}),
]


def run_standard(base: str, mode: str) -> int:
    rc = 0
    for plugin, stripe_width, profile in STANDARD_CONFIGS:
        fn = create if mode == "create" else check
        try:
            code = fn(base, plugin, stripe_width, dict(profile))
        except (EcError, OSError) as e:
            print(f"{plugin} {profile}: {e}", file=sys.stderr)
            code = 1
        if code:
            print(f"FAIL: {plugin} {profile}", file=sys.stderr)
            rc = 1
    return rc


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--create", action="store_true")
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--base", default=".")
    ap.add_argument("--plugin", "-p", default="jerasure")
    ap.add_argument("--stripe-width", "-s", type=int, default=4096)
    ap.add_argument(
        "--parameter", "-P", action="append", default=[], metavar="K=V"
    )
    ap.add_argument(
        "--standard",
        action="store_true",
        help="run the standing corpus configuration list instead of one profile",
    )
    args = ap.parse_args(argv)
    if not (args.create or args.check):
        ap.error("must specify either --check or --create")
    if args.standard:
        if args.parameter or args.plugin != "jerasure" or args.stripe_width != 4096:
            ap.error(
                "--standard runs the fixed STANDARD_CONFIGS list; it cannot "
                "be combined with --plugin/--stripe-width/-P"
            )
        rc = 0
        if args.create:
            rc |= run_standard(args.base, "create")
        if args.check:
            rc |= run_standard(args.base, "check")
        return rc
    profile: Profile = {}
    for p in args.parameter:
        if "=" not in p:
            ap.error(f"--parameter {p} needs K=V")
        key, val = p.split("=", 1)
        profile[key] = val
    rc = 0
    if args.create:
        rc |= create(args.base, args.plugin, args.stripe_width, profile)
    if args.check:
        rc |= check(args.base, args.plugin, args.stripe_width, profile)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
