"""bench.sh equivalent — sweep plugins x techniques x k/m grid.

Mirror of /root/reference/qa/workunits/erasure-code/bench.sh:40-57: runs the
benchmark harness over a parameter grid and emits one JSON line per run
(instead of flot JS) so results are machine-readable.

  python -m ceph_tpu.tools.bench_sweep --size 4096 --total-size 1048576
"""

from __future__ import annotations

import argparse
import json
import sys

from . import ec_benchmark


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bench_sweep", description=__doc__)
    p.add_argument("--size", type=int, default=4096, help="object size per op")
    p.add_argument(
        "--total-size", type=int, default=1 << 20, help="total bytes per config"
    )
    p.add_argument(
        "--plugins", default="tpu,jerasure", help="comma-separated plugin list"
    )
    p.add_argument("--ks", default="2,3,4,6,8,10")
    p.add_argument("--ms", default="1,2,3")
    p.add_argument("--workloads", default="encode,decode")
    args = p.parse_args(argv)

    techniques = {
        "tpu": ["reed_sol_van", "cauchy"],
        "jerasure": ["reed_sol_van", "cauchy_good"],
    }
    iterations = max(1, args.total_size // args.size)
    for plugin in args.plugins.split(","):
        for technique in techniques.get(plugin, [None]):
            for k in (int(x) for x in args.ks.split(",")):
                for m in (int(x) for x in args.ms.split(",")):
                    if m > k:
                        continue
                    for workload in args.workloads.split(","):
                        bench_args = [
                            "-p", plugin,
                            "-P", f"k={k}",
                            "-P", f"m={m}",
                            "-S", str(args.size),
                            "-i", str(iterations),
                            "-w", workload,
                            "-e", str(min(m, 2)),
                        ]
                        if technique:
                            bench_args += ["-P", f"technique={technique}"]
                        parser = ec_benchmark.build_parser()
                        opts = parser.parse_args(bench_args)
                        try:
                            ec = ec_benchmark.make_codec(opts)
                            if workload == "encode":
                                elapsed = ec_benchmark.run_encode(ec, opts)
                            else:
                                elapsed = ec_benchmark.run_decode(ec, opts)
                        except Exception as e:  # record failures, keep sweeping
                            print(
                                json.dumps(
                                    {
                                        "plugin": plugin,
                                        "technique": technique,
                                        "k": k,
                                        "m": m,
                                        "workload": workload,
                                        "error": str(e),
                                    }
                                )
                            )
                            continue
                        total = iterations * args.size
                        print(
                            json.dumps(
                                {
                                    "plugin": plugin,
                                    "technique": technique,
                                    "k": k,
                                    "m": m,
                                    "workload": workload,
                                    "seconds": round(elapsed, 6),
                                    "KiB": total / 1024,
                                    "MBps": round(total / max(elapsed, 1e-9) / 1e6, 1),
                                }
                            )
                        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
