"""bench.sh equivalent — sweep plugins x techniques x k/m grid.

Mirror of /root/reference/qa/workunits/erasure-code/bench.sh:40-57: runs the
benchmark harness over a parameter grid and emits one JSON line per run
(instead of flot JS) so results are machine-readable.

  python -m ceph_tpu.tools.bench_sweep --size 4096 --total-size 1048576
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from . import ec_benchmark

# The five BASELINE.md configs (LRC shape adjusted per the reference's own
# parse_kml constraints, see ec_corpus.py).
BASELINE_CONFIGS = [
    {
        "name": "jerasure_reed_sol_van_k4m2_4KiB",
        "plugin": "jerasure",
        "profile": {"k": "4", "m": "2", "technique": "reed_sol_van"},
        "size": 4 * 4096,
        "workloads": ("encode", "decode"),
    },
    {
        "name": "rs_8_3_cauchy_1MiB",
        "plugin": "tpu",
        "profile": {"k": "8", "m": "3", "technique": "cauchy"},
        "size": 1 << 20,
        # headline config: encode + decode at EVERY erasure count
        # (reference invocation: isa/README:36-47, decode e=1,2,3)
        "workloads": ("encode", "decode"),
        "erasure_counts": (1, 2, 3),
    },
    {
        # "64K stripes in flight" (BASELINE.md config 3): batching depth,
        # so the chunk is small (4 KiB) and the batch is what's measured
        "name": "rs_10_4_bulk_stripes",
        "plugin": "tpu",
        "profile": {"k": "10", "m": "4"},
        "size": 10 * 4096,  # 4 KiB chunks (chunk = size / k)
        "workloads": ("bulk",),
    },
    {
        "name": "clay_8_4_d11_subchunk_repair",
        "plugin": "clay",
        "profile": {"k": "8", "m": "4", "d": "11"},
        "size": 1 << 18,
        "workloads": ("repair",),
    },
    {
        "name": "lrc_12_3_l5_multi_failure",
        "plugin": "lrc",
        "profile": {"k": "12", "m": "3", "l": "5"},
        "size": 1 << 18,
        "workloads": ("encode", "decode"),
    },
]


def run_bulk(ec, size: int, batch: int, iters: int) -> tuple[float, int]:
    """BASELINE config 3: many stripes in flight through the held device
    executable (codec encode_array on a (S, k, L) batch) — the batched
    bulk-rebuild path, not per-object calls.

    Serial-chain methodology (same as bench.py): each launch's input is
    patched with bytes of the previous launch's parity under buffer
    donation, and a tiny device->host readback closes the timing window.
    Both guards matter on the axon backend, which caches identical launches
    and whose block_until_ready has been observed returning early — repeated
    same-input launches report impossible TB/s numbers.

    `batch` stripes stay in flight as a QUEUE of chained sub-launches of
    at most 4096 stripes (~160 MiB at 4 KiB chunks): one oversized launch
    through the axon tunnel is both wedge-prone (>256 MiB chains are what
    stuck the round-4 session) and unrepresentative — the OSD's pipeline
    submits bounded launches back-to-back, it does not build one 2.5 GB
    batch.
    """
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    k = ec.get_data_chunk_count()
    chunk = ec.get_chunk_size(size)
    sub = min(batch, 4096)
    rounds = -(-batch // sub)  # ceil: never measure fewer stripes than asked
    data = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (sub, k, chunk), dtype=np.uint8)
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(d, p):
        n = min(128, chunk)
        patch = (p[:1, :1, :n] ^ jnp.uint8(1)).reshape(1, 1, n)
        d2 = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
        return d2, ec.encode_array(d2)

    p = ec.encode_array(data)
    data, p = step(data, p)  # compile + warm
    jax.block_until_ready((data, p))
    t0 = time.perf_counter()
    for _ in range(iters):
        for _ in range(rounds):  # `rounds` launches queue without a sync
            data, p = step(data, p)
    jax.block_until_ready((data, p))
    _ = np.asarray(p[0, 0, :8])
    return time.perf_counter() - t0, sub * rounds * k * chunk * iters


def run_baseline(iterations: int, out=None) -> int:
    import jax

    platform = jax.devices()[0].platform
    # "64K stripes in flight" on real hardware; scaled down off-chip so the
    # CPU sweep stays tractable
    bulk_batch = 65536 if platform == "tpu" else 64

    def emit(rec: dict) -> None:
        line = json.dumps(rec)
        print(line, flush=True)
        if out is not None:
            out.write(line + "\n")
            out.flush()

    for cfg in BASELINE_CONFIGS:
        for workload in cfg["workloads"]:
            erasure_counts = (
                cfg.get("erasure_counts", (cfg.get("erasures", 2),))
                if workload == "decode"
                else (None,)
            )
            argv = ["-p", cfg["plugin"], "-S", str(cfg["size"]),
                    "-i", str(iterations)]
            for kv in cfg["profile"].items():
                argv += ["-P", f"{kv[0]}={kv[1]}"]
            opts = ec_benchmark.build_parser().parse_args(argv)
            try:
                ec = ec_benchmark.make_codec(opts)
            except (Exception, SystemExit) as e:
                emit({"config": cfg["name"], "workload": workload,
                      "platform": platform, "error": str(e)})
                continue
            for nerr in erasure_counts:
                rec = {
                    "config": cfg["name"],
                    "plugin": cfg["plugin"],
                    "profile": cfg["profile"],
                    "workload": workload,
                    "platform": platform,
                }
                try:
                    if workload == "encode":
                        elapsed = ec_benchmark.run_encode(ec, opts)
                        total = iterations * cfg["size"]
                    elif workload == "decode":
                        opts.erasures = min(
                            nerr, ec.get_coding_chunk_count()
                        )
                        rec["erasures"] = opts.erasures
                        elapsed = ec_benchmark.run_decode(ec, opts)
                        total = iterations * cfg["size"]
                    elif workload == "repair":
                        elapsed, bytes_read, bytes_repaired = (
                            ec_benchmark.run_repair(ec, opts)
                        )
                        total = iterations * cfg["size"]
                        rec["bytes_read"] = bytes_read
                        rec["bytes_repaired"] = bytes_repaired
                        rec["read_amplification"] = round(
                            bytes_read / max(1, bytes_repaired), 3
                        )
                    else:  # bulk
                        elapsed, total = run_bulk(
                            ec, cfg["size"], bulk_batch, max(2, iterations // 4)
                        )
                        rec["stripes_in_flight"] = bulk_batch
                    rec["seconds"] = round(elapsed, 6)
                    rec["MBps"] = round(total / max(elapsed, 1e-9) / 1e6, 1)
                except (Exception, SystemExit) as e:
                    # record failures, keep sweeping (run_decode/run_repair
                    # signal content mismatch via SystemExit)
                    rec["error"] = str(e)
                emit(rec)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bench_sweep", description=__doc__)
    p.add_argument("--size", type=int, default=4096, help="object size per op")
    p.add_argument(
        "--total-size", type=int, default=1 << 20, help="total bytes per config"
    )
    p.add_argument(
        "--plugins", default="tpu,jerasure", help="comma-separated plugin list"
    )
    p.add_argument("--ks", default="2,3,4,6,8,10")
    p.add_argument("--ms", default="1,2,3")
    p.add_argument("--workloads", default="encode,decode")
    p.add_argument(
        "--baseline",
        action="store_true",
        help="run the five BASELINE.md configs instead of the grid",
    )
    p.add_argument("--iterations", type=int, default=8)
    p.add_argument(
        "--out", default="",
        help="also append JSONL to this file (baseline mode only)",
    )
    args = p.parse_args(argv)

    if args.baseline:
        out = open(args.out, "a") if args.out else None
        try:
            return run_baseline(args.iterations, out=out)
        finally:
            if out is not None:
                out.close()

    techniques = {
        "tpu": ["reed_sol_van", "cauchy"],
        "jerasure": ["reed_sol_van", "cauchy_good"],
    }
    iterations = max(1, args.total_size // args.size)
    for plugin in args.plugins.split(","):
        for technique in techniques.get(plugin, [None]):
            for k in (int(x) for x in args.ks.split(",")):
                for m in (int(x) for x in args.ms.split(",")):
                    if m > k:
                        continue
                    for workload in args.workloads.split(","):
                        bench_args = [
                            "-p", plugin,
                            "-P", f"k={k}",
                            "-P", f"m={m}",
                            "-S", str(args.size),
                            "-i", str(iterations),
                            "-w", workload,
                            "-e", str(min(m, 2)),
                        ]
                        if technique:
                            bench_args += ["-P", f"technique={technique}"]
                        parser = ec_benchmark.build_parser()
                        opts = parser.parse_args(bench_args)
                        try:
                            ec = ec_benchmark.make_codec(opts)
                            if workload == "encode":
                                elapsed = ec_benchmark.run_encode(ec, opts)
                            else:
                                elapsed = ec_benchmark.run_decode(ec, opts)
                        except (Exception, SystemExit) as e:  # record, keep sweeping
                            print(
                                json.dumps(
                                    {
                                        "plugin": plugin,
                                        "technique": technique,
                                        "k": k,
                                        "m": m,
                                        "workload": workload,
                                        "error": str(e),
                                    }
                                )
                            )
                            continue
                        total = iterations * args.size
                        print(
                            json.dumps(
                                {
                                    "plugin": plugin,
                                    "technique": technique,
                                    "k": k,
                                    "m": m,
                                    "workload": workload,
                                    "seconds": round(elapsed, 6),
                                    "KiB": total / 1024,
                                    "MBps": round(total / max(elapsed, 1e-9) / 1e6, 1),
                                }
                            )
                        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
