"""vstart — dev cluster in one process, mirror of src/vstart.sh.

The reference's vstart.sh boots MON/MGR/OSD daemons on localhost for
development (defaults MON=3 OSD=3 MGR=1, vstart.sh:120-123).  Here the
daemons are asyncio objects in one process; `DevCluster` is the library
surface (used by tools and tests), and running the module starts a
cluster, writes its monmap to `./dev-cluster.json` for the `rados` /
`ceph` CLIs, and serves until interrupted:

    python -m ceph_tpu.tools.vstart --mons 1 --osds 3
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import socket

from ..common.config import Config
from ..mgr import Mgr
from ..mon import MonMap, Monitor
from ..osd.osd import OSD

CLUSTER_FILE = "dev-cluster.json"


def _free_port_addrs(n: int) -> dict[str, str]:
    addrs = {}
    socks = []
    for i in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        addrs[chr(ord("a") + i)] = f"127.0.0.1:{s.getsockname()[1]}"
    for s in socks:
        s.close()
    return addrs


class DevCluster:
    """mons + osds + mgr (+ optional MDS with its pools) in-process
    (the vstart topology; vstart.sh also boots MDS=1 by default)."""

    def __init__(
        self,
        n_mons: int = 1,
        n_osds: int = 3,
        with_mgr: bool = True,
        with_mds: bool = False,
        with_rgw: bool = False,
        n_mds: int = 2,  # daemons to boot when with_mds (rank 0 + standby)
        conf_overrides: dict | None = None,
        asok_dir: str = "",  # enable daemon admin sockets under this dir
    ):
        self.asok_dir = asok_dir
        self.n_mons = n_mons
        self.n_osds = n_osds
        self.with_mgr = with_mgr
        self.with_mds = with_mds
        self.with_rgw = with_rgw
        self.n_mds = n_mds
        self.conf_overrides = conf_overrides or {}
        self.monmap: MonMap | None = None
        self.mons: list[Monitor] = []
        self.osds: list[OSD] = []
        self.mgr: Mgr | None = None
        self.mds = None  # the active MDS (rank 0)
        self.mds_daemons: list = []
        self._mds_rados = None
        self._mds_radoses: list = []
        self.rgw_s3 = None
        self.rgw_swift = None
        self._rgw_rados = None

    async def start(self) -> MonMap:
        # ms_type applies cluster-wide (every daemon + client must share a
        # stack); inproc clusters use inproc monmap addresses.
        from ..msg.stack import _ALIASES

        raw = self.conf_overrides.get("ms_type", "async+posix")
        stack = self._stack = _ALIASES.get(raw, raw)
        if self.asok_dir:
            os.makedirs(self.asok_dir, exist_ok=True)
        if stack == "inproc":
            self.monmap = MonMap(
                addrs={
                    name: f"inproc:mon.{name}"
                    for name in ("abcdefghij"[: self.n_mons])
                }
            )
        else:
            self.monmap = MonMap(addrs=_free_port_addrs(self.n_mons))
        self.mons = [
            Monitor(
                name, self.monmap, election_timeout=0.3, stack=stack,
                admin_socket=self._asok(f"mon.{name}"),
            )
            for name in self.monmap.addrs
        ]
        for m in self.mons:
            await m.start()
        for m in self.mons:
            await m.wait_for_quorum()
        for i in range(self.n_osds):
            conf = Config(
                {
                    "name": f"osd.{i}",
                    **(
                        {"admin_socket": self._asok(f"osd.{i}")}
                        if self.asok_dir
                        else {}
                    ),
                    **self.conf_overrides,
                },
                env=False,
            )
            osd = OSD(i, self.monmap, conf=conf)
            await osd.start()
            self.osds.append(osd)
        for osd in self.osds:
            await osd.wait_for_up()
        if self.with_mgr:
            self.mgr = Mgr(
                "x",
                self.monmap,
                conf=Config(
                    {
                        "name": "mgr.x",
                        **(
                            {"admin_socket": self._asok("mgr.x")}
                            if self.asok_dir
                            else {}
                        ),
                        **self.conf_overrides,
                    },
                    env=False,
                ),
            )
            self.mgr.beacon_interval = 0.5
            await self.mgr.start()
            await self.mgr.wait_for_active()
            # standard module set (vstart.sh enables the same four)
            from ..mgr import (
                ClogModule,
                DashboardModule,
                IostatModule,
                MetricsHistoryModule,
                OrchestratorModule,
                ProgressModule,
                TelemetryModule,
            )
            from ..mgr.prometheus import PrometheusModule

            for module in (
                PrometheusModule(),
                DashboardModule(),
                TelemetryModule(),
                OrchestratorModule(),
                # recovery/backfill/scrub bars with rate + ETA in
                # `status`, PG_RECOVERY_STALLED health (ISSUE 8)
                ProgressModule(),
                # per-pool IO rates / top clients in `status`, the SLO
                # burn-rate health check, and the ceph_tpu_pool_*
                # scrape families (ISSUE 10) — registered here so the
                # operator path sees pool rates out of the box (the
                # same gap PR 6 closed for progress)
                IostatModule(),
                # mgr-resident perf history + trend sentinels (ISSUE
                # 14): `perf history ls/get` on the mgr asok, the
                # /api/perf_history dashboard route, and the
                # TPU_THROUGHPUT_REGRESSION family of checks work in
                # the operator path out of the box
                MetricsHistoryModule(),
                # cluster-event timeline (ISSUE 16): /api/log on the
                # dashboard + the ceph_tpu_clog_* / ceph_tpu_health_*
                # scrape families
                ClogModule(),
            ):
                self.mgr.register_module(module)
        if self.with_mds:
            # `ceph fs new` bootstrap: metadata + data pools, the fs map,
            # then the metadata servers — vstart.sh's MDS topology; 2
            # daemons give rank 0 + one standby for mon-driven failover
            # (MDSMonitor/FSMap, mon/mds_monitor.py)
            from ..client import Rados
            from ..mds import MDS

            self._mds_rados = Rados(
                self.monmap, name="client.mds-bootstrap", stack=self._stack
            )
            await self._mds_rados.connect()
            size = min(2, self.n_osds)
            await self._mds_rados.pool_create(
                "cephfs_metadata", "replicated", size=size, pg_num=4
            )
            await self._mds_rados.pool_create(
                "cephfs_data", "replicated", size=size, pg_num=8
            )
            rv, rs, _ = await self._mds_rados.mon_command(
                {"prefix": "fs new", "fs_name": "cephfs",
                 "metadata": "cephfs_metadata", "data": "cephfs_data"}
            )
            assert rv == 0, f"fs new failed: {rs}"
            for name in ("a", "b")[: max(1, self.n_mds)]:
                # each daemon gets its own RADOS client and binds its
                # assigned filesystem's pools at promotion (multi-fs FSMap)
                r = Rados(
                    self.monmap, name=f"client.mds-{name}", stack=self._stack
                )
                await r.connect()
                self._mds_radoses.append(r)
                d = MDS(
                    stack=self._stack, name=name, monmap=self.monmap, rados=r,
                    admin_socket=self._asok(f"mds.{name}"),
                )
                await d.start()
                self.mds_daemons.append(d)
            # rank 0 comes up once the fsmap names it
            deadline = asyncio.get_event_loop().time() + 10.0
            while not any(d.state == "active" for d in self.mds_daemons):
                if asyncio.get_event_loop().time() > deadline:
                    raise TimeoutError("no MDS became active")
                await asyncio.sleep(0.05)
            self.mds = next(
                d for d in self.mds_daemons if d.state == "active"
            )
        if self.with_rgw:
            # RGW=1: the S3 + Swift personalities over one gateway pool
            # (vstart.sh's radosgw boot)
            from ..client import Rados
            from ..rgw import ObjectGateway, S3Server, SwiftServer

            self._rgw_rados = Rados(
                self.monmap, name="client.rgw", stack=self._stack
            )
            await self._rgw_rados.connect()
            await self._rgw_rados.pool_create(
                "default.rgw.data", "replicated", size=min(2, self.n_osds),
                pg_num=8,
            )
            io = await self._rgw_rados.open_ioctx("default.rgw.data")
            gw = ObjectGateway(io)
            self.rgw_s3 = S3Server(gw, lc_interval=1.0)
            await self.rgw_s3.serve()
            self.rgw_swift = SwiftServer(gw)
            await self.rgw_swift.serve()
        return self.monmap

    def _asok(self, daemon: str) -> str:
        """Admin socket path for a daemon ('' when sockets are disabled)."""
        return f"{self.asok_dir}/{daemon}.asok" if self.asok_dir else ""

    async def stop(self) -> None:
        if self.rgw_s3 is not None:
            await self.rgw_s3.shutdown()
            self.rgw_s3 = None
        if self.rgw_swift is not None:
            await self.rgw_swift.shutdown()
            self.rgw_swift = None
        if self._rgw_rados is not None:
            await self._rgw_rados.shutdown()
            self._rgw_rados = None
        for d in self.mds_daemons:
            await d.stop()
        self.mds_daemons.clear()
        self.mds = None
        for r in self._mds_radoses:
            await r.shutdown()
        self._mds_radoses.clear()
        if self._mds_rados is not None:
            await self._mds_rados.shutdown()
        if self.mgr is not None:
            await self.mgr.stop()
        for osd in self.osds:
            if osd._running:
                await osd.stop()
        for m in self.mons:
            await m.stop()
        await asyncio.sleep(0.05)

    def write_cluster_file(self, path: str = CLUSTER_FILE) -> None:
        """Connection info for out-of-process CLIs."""
        info = {"mon_addrs": self.monmap.addrs}
        # `ceph tell <daemon> <cmd>` resolves admin sockets from here —
        # recorded from what each daemon ACTUALLY bound (a conf override
        # can point an OSD elsewhere than the asok_dir convention)
        socks = {
            **{
                f"mon.{m.name}": m._admin_socket_path
                for m in self.mons
                if m._admin_socket_path
            },
            **{
                f"osd.{o.whoami}": o.conf.get("admin_socket")
                for o in self.osds
                if o.conf.get("admin_socket")
            },
            **(
                {"mgr.x": self.mgr.conf.get("admin_socket")}
                if self.mgr is not None and self.mgr.conf.get("admin_socket")
                else {}
            ),
        }
        if socks:
            info["admin_sockets"] = socks
        if self.mds is not None:
            info["mds_addr"] = self.mds.addr
        socks = info.get("admin_sockets", {})
        for d in self.mds_daemons:
            if d._admin_socket_path:
                socks[f"mds.{d.name}"] = d._admin_socket_path
        if socks:
            info["admin_sockets"] = socks
        if self.rgw_s3 is not None:
            info["rgw_s3_endpoint"] = self.rgw_s3.addr
        if self.rgw_swift is not None:
            info["rgw_swift_endpoint"] = self.rgw_swift.addr
        with open(path, "w") as f:
            json.dump(info, f)


def load_monmap(path: str = CLUSTER_FILE) -> MonMap:
    with open(path) as f:
        info = json.load(f)
    return MonMap(addrs=info["mon_addrs"])


async def _main(args) -> None:
    cluster = DevCluster(
        args.mons, args.osds, with_mgr=not args.no_mgr, with_mds=args.mds,
        asok_dir=args.asok_dir,
    )
    await cluster.start()
    cluster.write_cluster_file(args.cluster_file)
    print(f"cluster up: {args.mons} mon(s), {args.osds} osd(s)"
          + (", 1 mds" if args.mds else "")
          + f"; monmap -> {args.cluster_file}")
    print("mon addrs:", ", ".join(cluster.monmap.addrs.values()))
    if cluster.mds is not None:
        print("mds addr:", cluster.mds.addr)
    try:
        while True:
            await asyncio.sleep(3600)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await cluster.stop()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mons", type=int, default=1)
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--no-mgr", action="store_true")
    p.add_argument("--mds", action="store_true",
                   help="boot an MDS with cephfs_metadata/cephfs_data pools")
    p.add_argument("--cluster-file", default=CLUSTER_FILE)
    p.add_argument("--asok-dir", default="dev-asok",
                   help="daemon admin sockets dir (ceph tell); '' disables")
    args = p.parse_args()
    try:
        asyncio.run(_main(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
