"""perf_compare — round-over-round performance trajectory gating
(ISSUE 14 layer 4).

The bench driver commits one ``BENCH_r<NN>.json`` per round, but until
now nothing ever COMPARED rounds: a TPU round that hit 23.4 GB/s and a
follow-up that silently fell to 2 GB/s looked equally "green".  This
tool is the comparator:

- ``load_rounds()`` parses the committed corpus (tolerating the legacy
  single-metric shape of early rounds and the rich multi-metric shape
  bench.py emits now) into flat per-round metric slices;
- ``compare()`` diffs a current round against the trailing rounds'
  same-platform best (throughput metrics are judged tpu-vs-tpu /
  cpu-vs-cpu — a CPU fallback round is a fallback, not a regression of
  the TPU story) and emits a machine-readable ``regressions`` slice
  that ``bench.py`` and ``tools/chaos.py`` fold into their tracked
  JSON, so the next TPU round is automatically judged against
  23.4 GB/s instead of silently resetting the story;
- ``--check`` validates the committed corpus (schema, parseability,
  finite numbers) with NO device and NO jax import — the tier-1 CI
  gate against malformed bench JSON or silent schema drift.

CLI:
    python -m ceph_tpu.tools.perf_compare --check
    python -m ceph_tpu.tools.perf_compare --current out.json [--ratio 0.8]
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys

# flagging threshold: value below ratio x same-platform baseline
# (higher-is-better) or above baseline / ratio (lower-is-better)
DEFAULT_RATIO = 0.8

# metric -> (path into the parsed bench JSON, direction,
# platform_scoped).  Throughput metrics compare same-platform only;
# chaos latency runs host-side whatever platform the bench child won.
METRICS: dict[str, tuple[tuple[str, ...], str, bool]] = {
    "rs_8_3_encode_GBps_per_chip": ((), "higher", True),
    "rs_8_3_decode_GBps_per_chip": (("decode",), "higher", True),
    "rs_8_3_verify_GBps_per_chip": (("verify",), "higher", True),
    "rs_8_3_encode_GBps_per_chip_pipelined": (("pipelined",), "higher", True),
    # fusion trajectory (ISSUE 18): aggregated end-to-end throughput
    # with super-launch fusion armed (multi-submitter backlog), and the
    # bucketed pad learner's steady-state waste fraction — waste is
    # lower-is-better and platform-independent (a stripe-count ratio)
    "rs_8_3_encode_GBps_per_chip_fused": (("fused",), "higher", True),
    "padding_waste_ratio": (("pad_waste",), "lower", False),
    "rs_8_3_encode_GBps_aggregate": (("multichip",), "higher", True),
    "rs_8_3_decode_GBps_aggregate": (("multichip", "decode"), "higher", True),
    "chaos_p99_ms": (("chaos", "chaos_p99_ms"), "lower", False),
    "recovery_occupancy": (("chaos", "recovery_occupancy"), "higher", False),
    # recovery-storm trajectory (ISSUE 15): whole-OSD rebuild time and
    # client p99 under the storm, both lower-is-better, folded from the
    # chaos JSON so a PR that slows rebuild (or lets it eat client
    # latency) flags against the committed best
    "chaos_rebuild_seconds": (("chaos", "rebuild_seconds"), "lower", False),
    "chaos_storm_p99_ms": (("chaos", "storm_p99_ms"), "lower", False),
    # gray-failure trajectory (ISSUE 17): client read p99 with one OSD's
    # shard reads delayed ~50x (hedged reads must keep beating the
    # injected delay round over round) and the hedge rate the window
    # paid for it — both lower-is-better, folded from the chaos JSON
    "chaos_gray_p99_ms": (("chaos", "gray_p99_ms"), "lower", False),
    "chaos_hedge_rate": (("chaos", "hedge_rate"), "lower", False),
    # write-path offload trajectory (ISSUE 20): device crc32c GB/s and
    # the fused compressor-transform + csum write path — both per-chip
    # throughputs, judged same-platform like the EC kernels
    "bluestore_csum_GBps_per_chip": (("csum",), "higher", True),
    "write_path_offload_GBps": (("offload",), "higher", True),
}

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def default_rounds_dir() -> str:
    """The repo root (where the driver commits BENCH_r*.json), resolved
    relative to this file: ceph_tpu/tools/ -> repo."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def metric_slice(parsed: dict) -> dict[str, float]:
    """Flatten one round's parsed bench JSON into {metric: value}.

    Handles both shapes: the legacy single-metric line
    (``{"metric": ..., "value": ...}``) and the current nested one
    where decode/verify/pipelined/multichip ride sub-objects carrying
    their own ``metric``/``value`` pairs (the chaos fold carries plain
    keys).  Unknown metrics are ignored — the comparator only judges
    what it has a direction for."""
    out: dict[str, float] = {}
    if not isinstance(parsed, dict):
        return out
    for name, (path, _direction, _scoped) in METRICS.items():
        node: object = parsed
        for key in path:
            if not isinstance(node, dict):
                node = None
                break
            node = node.get(key)
        if node is None:
            continue
        if path and path[0] == "chaos":
            # chaos keys are plain values, not {metric, value} objects
            value = node
        elif isinstance(node, dict):
            if node.get("metric") != name:
                continue
            value = node.get("value")
        else:
            continue
        if isinstance(value, (int, float)) and math.isfinite(value):
            out[name] = float(value)
    return out


def load_rounds(rounds_dir: str | None = None) -> list[dict]:
    """Parse every committed BENCH_r*.json into
    {round, rc, platform, metrics}, ordered by round number.  Rounds
    that failed (rc != 0 / no parsed slice) load with empty metrics —
    they are part of the trajectory, just not baselines."""
    rounds_dir = rounds_dir or default_rounds_dir()
    out: list[dict] = []
    for path in sorted(glob.glob(os.path.join(rounds_dir, "BENCH_r*.json"))):
        m = _ROUND_RE.search(os.path.basename(path))
        if m is None:
            continue
        with open(path) as f:
            doc = json.load(f)
        parsed = doc.get("parsed") or {}
        out.append({
            "round": int(m.group(1)),
            "rc": doc.get("rc"),
            "platform": parsed.get("platform"),
            "metrics": metric_slice(parsed),
        })
    out.sort(key=lambda r: r["round"])
    return out


def compare(
    current: dict,
    rounds: list[dict],
    ratio: float = DEFAULT_RATIO,
) -> dict:
    """Diff a current round's parsed slice against the trailing rounds.

    Returns the ``regressions`` slice bench/chaos fold:
    ``rounds_compared`` (which history was judged against),
    ``baselines`` (per metric: the same-platform best, with the round
    that set it), and ``flagged`` (metrics falling past ``ratio`` of
    their baseline).  A metric with no trailing baseline cannot flag —
    first rounds and platform switches compare against nothing, by
    design."""
    cur_platform = current.get("platform")
    cur_metrics = metric_slice(current)
    baselines: dict[str, dict] = {}
    for rnd in rounds:
        for name, value in rnd["metrics"].items():
            _path, direction, scoped = METRICS[name]
            if scoped and rnd["platform"] != cur_platform:
                continue
            best = baselines.get(name)
            better = (
                best is None
                or (direction == "higher" and value > best["value"])
                or (direction == "lower" and value < best["value"])
            )
            if better:
                baselines[name] = {
                    "value": value,
                    "round": rnd["round"],
                    "platform": rnd["platform"],
                }
    flagged: list[dict] = []
    for name, value in sorted(cur_metrics.items()):
        base = baselines.get(name)
        if base is None or base["value"] <= 0 or ratio <= 0:
            continue
        _path, direction, _scoped = METRICS[name]
        if direction == "higher":
            regressed = value < ratio * base["value"]
            vs = value / base["value"]
        else:
            regressed = value > base["value"] / ratio
            vs = base["value"] / value if value else 0.0
        if regressed:
            flagged.append({
                "metric": name,
                "value": value,
                "baseline": base["value"],
                "baseline_round": base["round"],
                "direction": direction,
                "vs_baseline": round(vs, 4),
            })
    return {
        "rounds_compared": [r["round"] for r in rounds],
        "platform": cur_platform,
        "ratio": ratio,
        "baselines": baselines,
        "flagged": flagged,
        "count": len(flagged),
    }


def compare_round(
    current: dict,
    rounds_dir: str | None = None,
    ratio: float = DEFAULT_RATIO,
) -> dict:
    """One-call fold for bench.py / chaos.py: load the committed corpus
    and compare `current` (a parsed-bench-shaped dict) against it."""
    return compare(current, load_rounds(rounds_dir), ratio=ratio)


def check_corpus(rounds_dir: str | None = None) -> list[str]:
    """Schema validation of the committed corpus (``--check``): every
    BENCH_r*.json must parse, carry the driver keys, and — when the
    round succeeded — a parsed slice whose metric values are finite
    non-negative numbers.  Returns problem strings (empty = clean)."""
    rounds_dir = rounds_dir or default_rounds_dir()
    problems: list[str] = []
    paths = sorted(glob.glob(os.path.join(rounds_dir, "BENCH_r*.json")))
    if not paths:
        return [f"no BENCH_r*.json rounds under {rounds_dir}"]
    for path in paths:
        name = os.path.basename(path)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            problems.append(f"{name}: unreadable/not JSON ({e})")
            continue
        if not isinstance(doc, dict):
            problems.append(f"{name}: top level is not an object")
            continue
        for key in ("n", "rc", "parsed"):
            if key not in doc:
                problems.append(f"{name}: missing driver key {key!r}")
        rc = doc.get("rc")
        parsed = doc.get("parsed")
        if rc == 0:
            if not isinstance(parsed, dict):
                problems.append(
                    f"{name}: rc=0 but parsed is not an object"
                )
                continue
            for key in ("metric", "value", "unit"):
                if key not in parsed:
                    problems.append(
                        f"{name}: parsed slice missing {key!r}"
                    )
            value = parsed.get("value")
            if not isinstance(value, (int, float)) or \
                    not math.isfinite(value) or value < 0:
                problems.append(
                    f"{name}: parsed.value {value!r} is not a finite "
                    "non-negative number"
                )
            for metric, mval in metric_slice(parsed).items():
                if mval < 0:
                    problems.append(
                        f"{name}: metric {metric} negative ({mval})"
                    )
        elif parsed not in (None, {}) and not isinstance(parsed, dict):
            problems.append(f"{name}: rc!=0 with non-object parsed slice")
    return problems


def trajectory(rounds_dir: str | None = None) -> list[dict]:
    """Per-round metric slices in round order (what `--check` prints):
    the committed story, machine-readable."""
    return [
        {
            "round": r["round"],
            "rc": r["rc"],
            "platform": r["platform"],
            "metrics": r["metrics"],
        }
        for r in load_rounds(rounds_dir)
    ]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds-dir", default="",
                    help="directory holding BENCH_r*.json "
                         "(default: the repo root)")
    ap.add_argument("--current", default="",
                    help="a bench JSON (the parsed slice / bench.py "
                         "output line) to judge against the corpus")
    ap.add_argument("--ratio", type=float, default=DEFAULT_RATIO,
                    help="regression threshold as a fraction of the "
                         "baseline (default %(default)s)")
    ap.add_argument("--check", action="store_true",
                    help="validate the committed corpus schema and exit "
                         "nonzero on any problem (the tier-1 gate)")
    args = ap.parse_args(argv)
    rounds_dir = args.rounds_dir or None
    if args.check:
        problems = check_corpus(rounds_dir)
        checked = len(glob.glob(os.path.join(
            rounds_dir or default_rounds_dir(), "BENCH_r*.json"
        )))
        print(json.dumps({
            "checked": checked,
            "ok": not problems,
            "problems": problems,
            "trajectory": trajectory(rounds_dir) if not problems else [],
        }, indent=2))
        return 1 if problems else 0
    if args.current:
        with open(args.current) as f:
            current = json.load(f)
        result = compare_round(current, rounds_dir, ratio=args.ratio)
        print(json.dumps(result, indent=2))
        return 1 if result["flagged"] else 0
    print(json.dumps(trajectory(rounds_dir), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
