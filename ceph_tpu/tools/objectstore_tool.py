"""objectstore tool — mirror of src/tools/ceph_objectstore_tool.cc.

Offline inspection and surgery on an OSD's object store (the reference
operates on a stopped OSD's BlueStore; here on a FileStore path):

    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op list
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op list --coll 1.0s0
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --coll C --oid O --op dump
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --coll C --oid O --op get-bytes --file out.bin
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --coll C --op export --file pg.export
    python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op import --file pg.export

Export/import carry a whole collection (the reference's PG export/import
for disaster recovery, ceph_objectstore_tool.cc do_export/do_import).
"""

from __future__ import annotations

import argparse
import base64
import json
import sys

from ..os.bluestore import BlueStore
from ..os.filestore import FileStore
from ..os.transaction import Transaction


def _store(path: str, kind: str = "filestore"):
    """Mount the store at `path` (--type, like the reference tool's
    objectstore selection)."""
    store = BlueStore(path) if kind == "bluestore" else FileStore(path)
    store.mount()
    return store


def op_list(store: FileStore, coll: str | None) -> None:
    if coll:
        for oid in sorted(store.list_objects(coll)):
            print(json.dumps([coll, oid]))
    else:
        for c in sorted(store.list_collections()):
            for oid in sorted(store.list_objects(c)):
                print(json.dumps([c, oid]))


def op_dump(store: FileStore, coll: str, oid: str) -> None:
    """Object metadata dump (the reference's `--op dump` JSON)."""
    size = store.stat(coll, oid)
    attrs = store.getattrs(coll, oid)
    omap = store.omap_get(coll, oid)
    print(
        json.dumps(
            {
                "coll": coll,
                "oid": oid,
                "size": size,
                "attrs": {k: base64.b64encode(v).decode() for k, v in attrs.items()},
                "omap": {k: base64.b64encode(v).decode() for k, v in omap.items()},
            },
            indent=2,
        )
    )


def op_get_bytes(store: FileStore, coll: str, oid: str, path: str) -> None:
    data = store.read(coll, oid, 0, 0)
    with open(path, "wb") as f:
        f.write(data)
    print(f"wrote {len(data)} bytes", file=sys.stderr)


def op_set_bytes(store: FileStore, coll: str, oid: str, path: str) -> None:
    with open(path, "rb") as f:
        data = f.read()
    txn = Transaction().remove(coll, oid).touch(coll, oid).write(coll, oid, 0, data)
    store.queue_transaction(txn)
    print(f"stored {len(data)} bytes", file=sys.stderr)


def op_remove(store: FileStore, coll: str, oid: str) -> None:
    store.queue_transaction(Transaction().remove(coll, oid))


def op_export(store: FileStore, coll: str, path: str) -> None:
    """Collection export (do_export): every object with data+attrs+omap."""
    objects = []
    for oid in sorted(store.list_objects(coll)):
        objects.append(
            {
                "oid": oid,
                "data": base64.b64encode(store.read(coll, oid, 0, 0)).decode(),
                "attrs": {
                    k: base64.b64encode(v).decode()
                    for k, v in store.getattrs(coll, oid).items()
                },
                "omap": {
                    k: base64.b64encode(v).decode()
                    for k, v in store.omap_get(coll, oid).items()
                },
            }
        )
    with open(path, "w") as f:
        json.dump({"coll": coll, "objects": objects}, f)
    print(f"exported {len(objects)} objects from {coll}", file=sys.stderr)


def op_import(store: FileStore, path: str) -> None:
    with open(path) as f:
        dump = json.load(f)
    coll = dump["coll"]
    txn = Transaction()
    if not store.collection_exists(coll):
        txn.create_collection(coll)
    for obj in dump["objects"]:
        oid = obj["oid"]
        txn.remove(coll, oid).touch(coll, oid)
        txn.write(coll, oid, 0, base64.b64decode(obj["data"]))
        for k, v in obj["attrs"].items():
            txn.setattr(coll, oid, k, base64.b64decode(v))
        if obj["omap"]:
            txn.omap_setkeys(
                coll, oid, {k: base64.b64decode(v) for k, v in obj["omap"].items()}
            )
    store.queue_transaction(txn)
    print(f"imported {len(dump['objects'])} objects into {coll}", file=sys.stderr)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-path", required=True)
    p.add_argument("--type", default="filestore",
                   choices=["filestore", "bluestore"],
                   help="objectstore backend at --data-path")
    p.add_argument("--op", required=True,
                   help="list|dump|get-bytes|set-bytes|remove|export|import")
    p.add_argument("--coll")
    p.add_argument("--oid")
    p.add_argument("--file")
    args = p.parse_args(argv)
    store = _store(args.data_path, args.type)
    try:
        if args.op == "list":
            op_list(store, args.coll)
        elif args.op == "dump":
            op_dump(store, args.coll, args.oid)
        elif args.op == "get-bytes":
            op_get_bytes(store, args.coll, args.oid, args.file)
        elif args.op == "set-bytes":
            op_set_bytes(store, args.coll, args.oid, args.file)
        elif args.op == "remove":
            op_remove(store, args.coll, args.oid)
        elif args.op == "export":
            op_export(store, args.coll, args.file)
        elif args.op == "import":
            op_import(store, args.file)
        else:
            print(f"unknown op {args.op!r}", file=sys.stderr)
            return 1
        return 0
    finally:
        store.umount()


if __name__ == "__main__":
    sys.exit(main())
