"""ceph CLI — mirror of src/ceph.in (the admin command shell).

Sends JSON commands to the monitors exactly as the reference CLI builds
cmdmaps, printing the reply:

    python -m ceph_tpu.tools.ceph_cli status
    python -m ceph_tpu.tools.ceph_cli osd dump
    python -m ceph_tpu.tools.ceph_cli osd pool create mypool replicated
    python -m ceph_tpu.tools.ceph_cli osd erasure-code-profile set p1 k=4 m=2
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..mon.client import MonClient
from .vstart import CLUSTER_FILE, load_monmap

# prefix word-counts the mon understands, longest match first
_PREFIXES = [
    "osd erasure-code-profile set",
    "osd erasure-code-profile get",
    "osd erasure-code-profile ls",
    "osd erasure-code-profile rm",
    "osd pool create",
    "osd pool set-quota",
    "osd pool set",
    "osd pool ls",
    "osd pool get",
    "osd pool application enable",
    "osd pool application get",
    "osd df",
    "log last",
    "health history",
    "health mute",
    "health unmute",
    "health",
    "osd pool rm",
    "osd tier add",
    "osd tier remove-overlay",
    "osd tier remove",
    "osd tier cache-mode",
    "osd tier set-overlay",
    "osd blocklist add",
    "osd blocklist rm",
    "osd blocklist ls",
    "osd reweight",
    "osd dump",
    "osd out",
    "osd in",
    "fs new",
    "fs rm",
    "fs status",
    "quorum_status",
    "status",
    "df",
]


def build_cmd(words: list[str]) -> dict:
    """Tokens → cmdmap (ceph.in's json_command translation)."""
    joined = " ".join(words)
    for prefix in _PREFIXES:
        if joined == prefix or joined.startswith(prefix + " "):
            rest = words[len(prefix.split()):]
            cmd: dict = {"prefix": prefix}
            if prefix == "osd pool create":
                for i, k in enumerate(["pool", "pool_type", "erasure_code_profile"]):
                    if i < len(rest):
                        cmd[k] = rest[i]
            elif prefix == "osd pool set":
                for i, k in enumerate(["pool", "var", "val"]):
                    if i < len(rest):
                        cmd[k] = rest[i]
            elif prefix == "osd pool set-quota":
                for i, k in enumerate(["pool", "field", "val"]):
                    if i < len(rest):
                        cmd[k] = rest[i]
                if "yes_i_really_mean_it" in rest:
                    cmd["yes_i_really_mean_it"] = True
            elif prefix in ("osd pool rm",):
                if rest:
                    cmd["pool"] = rest[0]
            elif prefix == "osd pool get":
                for i, k in enumerate(["pool", "var"]):
                    if i < len(rest):
                        cmd[k] = rest[i]
            elif prefix.startswith("osd pool application"):
                for i, k in enumerate(["pool", "app"]):
                    if i < len(rest):
                        cmd[k] = rest[i]
            elif prefix in ("osd tier add", "osd tier remove"):
                cmd["pool"], cmd["tierpool"] = rest[0], rest[1]
            elif prefix == "osd tier cache-mode":
                cmd["pool"], cmd["mode"] = rest[0], rest[1]
            elif prefix == "osd tier set-overlay":
                cmd["pool"], cmd["overlaypool"] = rest[0], rest[1]
            elif prefix == "osd tier remove-overlay":
                cmd["pool"] = rest[0]
            elif prefix in ("osd blocklist add", "osd blocklist rm"):
                if rest:
                    cmd["entity"] = rest[0]
            elif prefix == "osd reweight":
                cmd["id"], cmd["weight"] = rest[0], rest[1]
            elif prefix in ("osd out", "osd in"):
                cmd["id"] = rest[0]
            elif prefix == "fs new":
                for i, k in enumerate(["fs_name", "metadata", "data"]):
                    if i < len(rest):
                        cmd[k] = rest[i]
            elif prefix == "fs rm":
                if rest:
                    cmd["fs_name"] = rest[0]
            elif prefix == "health":
                # `ceph health detail`: per-daemon breakdown of each check
                if rest and rest[0] == "detail":
                    cmd["detail"] = True
            elif prefix == "log last":
                # `ceph log last [n] [channel] [severity]` — positional n
                # first, then channel/severity keywords in either order
                for r in rest:
                    if r.isdigit():
                        cmd["num"] = int(r)
                    elif r in ("cluster", "audit"):
                        cmd["channel"] = r
                    elif r in ("debug", "info", "warn", "error"):
                        cmd["level"] = r
            elif prefix == "health mute":
                # `ceph health mute <CODE> [<ttl>] [--sticky]`
                for r in rest:
                    if r == "--sticky":
                        cmd["sticky"] = True
                    elif "code" not in cmd:
                        cmd["code"] = r
                    else:
                        cmd["ttl"] = r
            elif prefix == "health unmute":
                if rest:
                    cmd["code"] = rest[0]
            elif prefix == "health history":
                if rest and rest[0].isdigit():
                    cmd["num"] = int(rest[0])
            elif prefix.startswith("osd erasure-code-profile"):
                if rest:
                    cmd["name"] = rest[0]
                    kvs = [r for r in rest[1:] if "=" in r]
                    if kvs:
                        cmd["profile"] = kvs
            return cmd
    return {"prefix": joined}


def _run_tell(args) -> int:
    """`ceph tell <daemon> <cmd> [k=v ...]` — route a command straight to
    a daemon's admin socket (ceph.in's tell path; the daemon must have
    been started with admin sockets, e.g. vstart --asok-dir)."""
    from ..common.admin_socket import admin_command

    daemon, words = args.words[1], args.words[2:]
    with open(args.cluster_file) as f:
        info = json.load(f)
    socks = info.get("admin_sockets", {})
    path = socks.get(daemon)
    if path is None:
        print(
            f"no admin socket for {daemon!r} (have: {sorted(socks)})",
            file=sys.stderr,
        )
        return 1
    prefix_words = [w for w in words if "=" not in w]
    kwargs = dict(w.split("=", 1) for w in words if "=" in w)
    kwargs.pop("timeout", None)  # reserved: the CLI's --timeout flag wins
    try:
        result = admin_command(
            path, " ".join(prefix_words), timeout=args.timeout, **kwargs
        )
    except Exception as e:
        # daemon down, stale socket, unknown command, hook error — all
        # surface as one clean line, not a traceback
        print(f"tell {daemon} failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(result, indent=2))
    return 0


async def _run(args) -> int:
    monmap = load_monmap(args.cluster_file)
    client = MonClient("client.ceph-cli", monmap)
    try:
        cmd = build_cmd(args.words)
        rv, rs, out = await client.command(cmd, timeout=args.timeout)
        if out:
            try:
                print(json.dumps(json.loads(out.decode()), indent=2))
            except (json.JSONDecodeError, UnicodeDecodeError):
                sys.stdout.buffer.write(out)
        if rs:
            print(rs, file=sys.stderr)
        return 0 if rv == 0 else 1
    finally:
        await client.msgr.shutdown()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--cluster-file", default=CLUSTER_FILE)
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument("words", nargs="+")
    args = p.parse_args()
    if args.words[0] == "tell":
        if len(args.words) < 3:
            print("usage: ceph tell <daemon> <command> [k=v ...]",
                  file=sys.stderr)
            sys.exit(1)
        sys.exit(_run_tell(args))
    sys.exit(asyncio.run(_run(args)))


if __name__ == "__main__":
    main()
