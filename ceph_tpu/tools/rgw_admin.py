"""radosgw-admin CLI — mirror of src/rgw/rgw_admin.cc (the admin tool).

Operates directly on the gateway's RADOS state (users, buckets, index,
lifecycle), like the reference tool does through RGWRados:

    python -m ceph_tpu.tools.rgw_admin -p rgwpool user create --uid alice
    python -m ceph_tpu.tools.rgw_admin -p rgwpool user info --uid alice
    python -m ceph_tpu.tools.rgw_admin -p rgwpool bucket list
    python -m ceph_tpu.tools.rgw_admin -p rgwpool bucket stats --bucket b1
    python -m ceph_tpu.tools.rgw_admin -p rgwpool lc process
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..client import Rados
from ..rgw import ObjectGateway, RgwError
from .vstart import CLUSTER_FILE, load_monmap


async def _run(args) -> int:
    client = Rados(load_monmap(args.cluster_file), name="client.rgw-admin")
    await client.connect()
    try:
        ioctx = await client.open_ioctx(args.pool)
        gw = ObjectGateway(ioctx)
        words = args.words
        area = words[0]
        op = words[1] if len(words) > 1 else ""
        try:
            if area == "user":
                if op == "create":
                    user = await gw.create_user(
                        args.uid, display_name=args.display_name
                    )
                    print(json.dumps(user, indent=2))
                elif op == "info":
                    print(json.dumps(await gw.get_user(args.uid), indent=2))
                elif op == "list":
                    users = await gw._load("rgw.users")
                    for uid in sorted(users):
                        print(uid)
                else:
                    print(f"unknown user op {op!r}", file=sys.stderr)
                    return 1
            elif area == "bucket":
                if op == "list":
                    for b in await gw.list_buckets(
                        owner=args.uid if args.uid else None
                    ):
                        print(b)
                elif op == "stats":
                    listing = await gw.list_objects(
                        args.bucket, actor=args.uid or None, max_keys=1 << 30
                    )
                    print(
                        json.dumps(
                            {
                                "bucket": args.bucket,
                                "num_objects": len(listing["contents"]),
                                "size": sum(
                                    c["size"] for c in listing["contents"]
                                ),
                            },
                            indent=2,
                        )
                    )
                elif op == "rm":
                    await gw.delete_bucket(args.bucket)
                else:
                    print(f"unknown bucket op {op!r}", file=sys.stderr)
                    return 1
            elif area == "lc":
                if op == "process":
                    n = await gw.process_lifecycle()
                    print(f"expired {n} objects")
                elif op == "list":
                    buckets = await gw._load("rgw.buckets")
                    for b, info in sorted(buckets.items()):
                        for rule in info.get("lifecycle", []):
                            print(json.dumps({"bucket": b, **rule}))
                else:
                    print(f"unknown lc op {op!r}", file=sys.stderr)
                    return 1
            else:
                print(f"unknown area {area!r}", file=sys.stderr)
                return 1
        except RgwError as e:
            print(f"radosgw-admin: {e}", file=sys.stderr)
            return 1
        return 0
    finally:
        await client.shutdown()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-p", "--pool", required=True)
    p.add_argument("--cluster-file", default=CLUSTER_FILE)
    p.add_argument("--uid", default="")
    p.add_argument("--display-name", default="")
    p.add_argument("--bucket", default="")
    p.add_argument(
        "words", nargs="+",
        help="user <create|info|list> | bucket <list|stats|rm> | "
        "lc <process|list>",
    )
    sys.exit(asyncio.run(_run(p.parse_args())))


if __name__ == "__main__":
    main()
