"""rados CLI — mirror of src/tools/rados (put/get/rm/stat/ls/df/bench).

Targets a running cluster via the vstart cluster file:

    python -m ceph_tpu.tools.rados_cli -p mypool put obj1 ./file
    python -m ceph_tpu.tools.rados_cli -p mypool ls
    python -m ceph_tpu.tools.rados_cli -p mypool bench 5 write

`bench` mirrors `rados bench` output shape: total writes, bandwidth,
average latency (src/tools/rados/rados.cc bench command → ObjBencher).
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time

from ..client import Rados
from .vstart import CLUSTER_FILE, load_monmap


async def _run(args) -> int:
    client = Rados(load_monmap(args.cluster_file), name=f"client.rados-cli")
    await client.connect()
    try:
        if args.op == "lspools":
            for name in await client.pool_list():
                print(name)
            return 0
        if args.op == "mkpool":
            await client.pool_create(args.pool, "replicated", size=args.size)
            print(f"pool {args.pool!r} created")
            return 0
        if args.op == "df":
            # rados df: the mon-served PGMap digest (ceph df shape)
            import json as _json

            rv, rs, out = await client.mon_command({"prefix": "df"})
            if rv:
                print(rs, file=sys.stderr)
                return 1
            digest = _json.loads(out.decode() or "{}")
            print(f"{'POOL':<20}{'STORED':>12}{'OBJECTS':>10}{'USED':>12}")
            for name, st in sorted(digest.get("pools", {}).items()):
                print(
                    f"{name:<20}{st['stored']:>12}{st['objects']:>10}"
                    f"{st['used_raw']:>12}"
                )
            print(f"total_used_raw {digest.get('total_used_raw', 0)}")
            return 0
        ioctx = await client.open_ioctx(args.pool)
        if args.op == "put":
            with open(args.args[1], "rb") as f:
                data = f.read()
            await ioctx.write_full(args.args[0], data)
            print(f"wrote {len(data)} bytes to {args.args[0]}")
        elif args.op == "get":
            data = await ioctx.read(args.args[0])
            if len(args.args) > 1:
                with open(args.args[1], "wb") as f:
                    f.write(data)
            else:
                sys.stdout.buffer.write(data)
        elif args.op == "rm":
            await ioctx.remove(args.args[0])
        elif args.op == "stat":
            size = await ioctx.stat(args.args[0])
            print(f"{args.pool}/{args.args[0]} size {size}")
        elif args.op == "ls":
            for oid in await ioctx.list_objects():
                print(oid)
        elif args.op == "listwatchers":
            for w in await ioctx.list_watchers(args.args[0]):
                print(f"watcher={w['watcher']} cookie={w['cookie']}")
        elif args.op == "listomapkeys":
            for k in await ioctx.omap_get_keys(args.args[0]):
                print(k)
        elif args.op == "listomapvals":
            for k, v in sorted((await ioctx.omap_get_vals(args.args[0])).items()):
                print(f"{k}\n       value ({len(v)} bytes) :")
                sys.stdout.buffer.write(v + b"\n")
        elif args.op == "setomapval":
            await ioctx.omap_set(
                args.args[0], {args.args[1]: args.args[2].encode()}
            )
        elif args.op == "rmomapkey":
            await ioctx.omap_rm_keys(args.args[0], [args.args[1]])
        elif args.op == "clearomap":
            await ioctx.omap_clear(args.args[0])
        elif args.op == "cache-flush":
            # rados cache-flush: write a dirty cache-tier object back
            await ioctx.cache_flush(args.args[0])
            print(f"flushed {args.args[0]}")
        elif args.op == "cache-evict":
            await ioctx.cache_evict(args.args[0])
            print(f"evicted {args.args[0]}")
        elif args.op == "bench":
            await _bench(ioctx, int(args.args[0]), args.args[1] if len(args.args) > 1 else "write")
        else:
            print(f"unknown op {args.op!r}", file=sys.stderr)
            return 1
        return 0
    finally:
        await client.shutdown()


async def _bench(ioctx, seconds: int, mode: str, obj_size: int = 4 << 20) -> None:
    """rados bench (ObjBencher::aio_bench, sequential here)."""
    deadline = time.monotonic() + seconds
    payload = b"\xab" * obj_size
    count = 0
    latencies = []
    if mode == "write":
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            await ioctx.write_full(f"benchmark_data_{count}", payload)
            latencies.append(time.monotonic() - t0)
            count += 1
    else:  # read back what a prior write bench left, cycling over them
        existing = [
            o for o in await ioctx.list_objects() if o.startswith("benchmark_data_")
        ]
        if not existing:
            print("no benchmark objects; run a write bench first")
            return
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            await ioctx.read(existing[count % len(existing)])
            latencies.append(time.monotonic() - t0)
            count += 1
    elapsed = sum(latencies) or 1e-9
    mb = count * obj_size / (1 << 20)
    print(f"Total {mode}s made:     {count}")
    print(f"{mode.capitalize()} size:            {obj_size}")
    print(f"Bandwidth (MB/sec):    {mb / elapsed:.3f}")
    print(f"Average Latency(s):    {elapsed / max(count, 1):.4f}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-p", "--pool", default="")
    p.add_argument("--cluster-file", default=CLUSTER_FILE)
    p.add_argument("--size", type=int, default=3, help="pool size for mkpool")
    p.add_argument(
        "op",
        help="put|get|rm|stat|ls|bench|lspools|mkpool|cache-flush|cache-evict"
        "|listomapkeys|listomapvals|setomapval|rmomapkey|clearomap",
    )
    p.add_argument("args", nargs="*")
    sys.exit(asyncio.run(_run(p.parse_args())))


if __name__ == "__main__":
    main()
