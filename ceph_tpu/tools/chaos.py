"""Chaos / recovery-under-load harness — ROADMAP item 4's first rung.

Boots a real multi-OSD vstart-style cluster (loopback messengers, live
mon/mgr/OSD daemons) and drives mixed client load while injecting the
failure modes the wired FaultInjector seams expose (ISSUE 7):

- probabilistic socket failures (`msgr.send`, the ms_inject_socket_
  failures analog) under lossless-policy resend,
- objectstore EIO bursts (`os.read`) driving EC redundant-read
  escalation and reconstruction,
- a gray-OSD phase (ISSUE 17): one OSD's shard reads are delayed ~50x
  (`ec.sub_read` delay_ms mode, scoped to the victim daemon) while its
  heartbeats stay on time — adaptive hedged reads keep client p99
  bounded under the injected delay, the laggy detector raises
  OSD_SLOW_PEER on exactly the victim and clears it when the delay
  lifts, and a healthy control window proves hedging is quiescent,
- device coding-launch failures (`codec.launch`) driving the
  DEGRADED-backend host fallback + re-probe self-heal,
- an offload-fallback phase (ISSUE 20): launch faults armed while the
  device crc32c and batched-compressor services have launches in
  flight under mixed load with `bluestore_csum_offload` switched on
  live — stored csums stay byte-identical to utils/crc32c, compressed
  blobs round-trip, the offload_inflight mempool drains to zero, and
  client p99 stays bounded,
- a deep-scrub-under-load phase (ISSUE 9): silent shard corruption is
  planted on disk, every primary deep-scrubs (TPU-offloaded parity
  verify through the VerifyAggregator's background QoS lane) WHILE
  client writes keep flowing — the phase asserts the corruption is
  detected, that verify launches aggregated (fewer launches than
  objects), and that client p99 stayed within the QoS bound while the
  scrub stream ran,
- an OSD flap (stop, degraded writes, restart on the old store) driving
  peering + recovery pushes,
- a whole-OSD recovery storm (ISSUE 15): an OSD dies for good, the
  mon's dampened down→out sweep remaps it, and every surviving
  primary's recovery-storm controller batches the flooded missing sets
  into cross-PG decode waves while mixed load keeps flowing — with
  recovery-path wedges (`ec.recover_push`, `peering.msg`) armed
  mid-storm; asserts the rebuild-time bound AND the client-p99 bound
  simultaneously, and wave batching (decode launches < objects
  recovered, witnessed by flight records),
- a flapping-OSD phase: rapid bounces accumulate markdown history, the
  dampened grace grows exponentially (map stays stable: zero
  auto-outs), then the same OSD dies for real and is still outed past
  the longer grace — dampening delays churn without orphaning data.

The run is SEEDED and deterministic in its decision sequence (payloads,
object names, injection arming order all come from one rng; socket-fault
draws use the injector's own fixed-seed rng), asserts convergence — all
PGs active+clean, every acked write readable byte-identical, health
clear of stuck SLOW_OPS and TPU_BACKEND_DEGRADED — and reports
machine-readable metrics pulled from the PR-1 histogram substrate: p99
client op latency, recovery launch occupancy, host-fallback counts,
messenger resends.

`--smoke` is the fast, seed-fixed variant tier-1 runs
(tests/test_chaos_smoke.py); the full mode scales objects/rounds up.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import time


from ceph_tpu.tools.vstart import _free_port_addrs


def _osd_conf(i: int):
    from ceph_tpu.common.config import Config

    return Config(
        {
            "name": f"osd.{i}",
            # a real (in-memory) BlueStore, not MemStore: the offload
            # phase (ISSUE 20) switches bluestore_csum_offload on live
            # and verifies the csums the store actually persisted
            "osd_objectstore": "bluestore",
            "osd_heartbeat_interval": 0.1,
            "osd_heartbeat_grace": 0.6,
            # tight deadline so an (injected) wedged launch falls back
            # within the run instead of riding the 20 s default
            "ec_tpu_launch_timeout_ms": 5000,
            "ec_tpu_probe_interval_ms": 200,
            # recovery-storm controller (ISSUE 15): engage at smoke
            # scale, small waves, quick stalled-push retry so the
            # armed ec.recover_push wedge self-heals within the run
            "osd_recovery_storm_min_objects": 6,
            "osd_recovery_storm_wave_objects": 8,
            "osd_recovery_storm_max_inflight": 24,
            "osd_recovery_storm_slo_target_ms": 2000.0,
            "osd_recovery_push_retry_sec": 0.5,
        },
        env=False,
    )


def _mon_conf(cfg: dict):
    """Mon config for the storm/flap phases (ISSUE 15): a fast tick,
    flap dampening armed, and the down→out sweep DISABLED until the
    storm phase arms it (runtime `conf.set`) — the early phases' flap
    must never race the auto-out."""
    from ceph_tpu.common.config import Config

    return Config(
        {
            "name": "mon.chaos",
            "mon_tick_interval": 0.2,
            "mon_osd_down_out_interval": 0.0,
            "mon_osd_flap_window": 120.0,
            "mon_osd_flap_backoff": 2.0,
            "mon_osd_flap_max_auto_out_per_tick": 2,
        },
        env=False,
    )


def _osd_complaint_default() -> float:
    from ceph_tpu.common.options import OPTIONS

    return float(OPTIONS["osd_op_complaint_time"].default)


async def _wait_until(pred, timeout: float, what: str) -> None:
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise TimeoutError(f"chaos: timed out waiting for {what}")
        await asyncio.sleep(0.02)


def _p99_from_histogram(dump: dict) -> float:
    """99th-percentile upper bound from a PerfHistogram.dump() payload
    (cumulative [le, count] buckets): the smallest bound covering >= 99%
    of samples.  inf means the tail spilled into the overflow bucket."""
    h = (dump or {}).get("histogram") or {}
    buckets = h.get("buckets") or []
    total = h.get("count") or 0
    if not total:
        return 0.0
    want = 0.99 * total
    for le, cum in buckets:
        if cum >= want:
            return float("inf") if le == "+Inf" else float(le)
    return float("inf")


async def _run(cfg: dict) -> dict:
    from ceph_tpu.client import Rados
    from ceph_tpu.common.fault_injector import global_injector
    from ceph_tpu.mgr import Mgr
    from ceph_tpu.mon import MonMap, Monitor
    from ceph_tpu.ops import dispatch as ec_dispatch
    from ceph_tpu.ops.guard import device_guard
    from ceph_tpu.osd.osd import OSD

    rng = random.Random(cfg["seed"])
    inj = global_injector()
    report: dict = {
        "seed": cfg["seed"],
        "smoke": cfg["smoke"],
        "osds": cfg["osds"],
        "objects": cfg["objects"],
        "converged": False,
        "lost_writes": -1,
        "events": [],
    }
    # dynamic lock-order validation rides every chaos run (ISSUE 12):
    # the concurrent aggregator/scheduler/pipeline/cache stack under
    # faults is exactly where a latent ordering cycle would surface.
    # Violations are counted process-wide, so baseline for the embedded
    # tier-1 smoke (tests/test_lockdep.py raises some on purpose).
    from ceph_tpu.common import lockdep

    lockdep.enable()
    lockdep_violations0 = lockdep.violations()
    fallback0 = ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"]
    # run-start baselines: the dispatch counters and flight recorder are
    # process-lifetime, and an embedded run (tests/test_chaos_smoke.py in
    # a shared pytest process) must not report OTHER tests' launches as
    # chaos metrics
    decode0 = ec_dispatch.DECODE_LAUNCHES.snapshot()
    from ceph_tpu.ops.flight_recorder import flight_recorder

    flight_recorder().reset()
    # HBM mempool ledger (ISSUE 13): rebase the peaks so the reported
    # high-water mark is a property of THIS run, and so the end-of-run
    # leak assertion measures this run's drains
    from ceph_tpu.common.mempool import ledger as hbm_ledger

    hbm = hbm_ledger()
    hbm.reset_peaks()

    monmap = MonMap(addrs=_free_port_addrs(1))
    mons = [
        Monitor(n, monmap, election_timeout=0.3, conf=_mon_conf(cfg))
        for n in monmap.addrs
    ]
    for m in mons:
        await m.start()
    for m in mons:
        await m.wait_for_quorum()
    osds = [OSD(i, monmap, conf=_osd_conf(i)) for i in range(cfg["osds"])]
    for o in osds:
        await o.start()
    for o in osds:
        await o.wait_for_up()
    mgr = Mgr("x", monmap)
    mgr.beacon_interval = 0.1
    # progress module (ISSUE 8): per-PG recovery bars with rate/ETA ride
    # the digest; the harness reports how many events it observed so the
    # flap phase's recovery is visibly tracked end to end
    from ceph_tpu.mgr.progress import ProgressModule

    progress_mod = ProgressModule()
    mgr.register_module(progress_mod)
    # iostat module (ISSUE 10): per-pool/per-client rates + SLO burn
    # rates over short pinned windows so the mixed-load phase can
    # assert the burn stays under bound within a smoke-scale run.  The
    # latency target is the scrub QoS bound — generous for shared CI
    # hosts; the assertion catches seconds-scale starvation, not noise.
    from ceph_tpu.mgr.iostat import IostatModule

    iostat_mod = IostatModule(
        window_sec=2.0,
        slo_target_ms=cfg["slo_target_ms"],
        slo_fast_window_sec=0.5,
        slo_slow_window_sec=1.5,
    )
    mgr.register_module(iostat_mod)
    # metrics-history module (ISSUE 14): short pinned trend windows so
    # the sentinels genuinely EVALUATE inside a smoke-scale run (the
    # defaults would hold fire for 75 s) — a healthy converged chaos
    # run must end with history_sentinels_fired == 0.  The regression
    # ratio is pinned low and the volume floor high because chaos load
    # is deliberately bursty: the assertion exists to catch spurious
    # raises on phase transitions, not to benchmark.
    from ceph_tpu.mgr.metrics_history import MetricsHistoryModule

    history_mod = MetricsHistoryModule(
        window_sec=2.0,
        baseline_sec=6.0,
        regression_ratio=0.2,
        occupancy_ratio=0.2,
        queue_wait_factor=50.0,
        min_launch_rate=0.5,
    )
    mgr.register_module(history_mod)
    await mgr.start()
    await mgr.wait_for_active()
    progress_pgs_seen: set[tuple] = set()

    client = Rados(monmap)
    await client.connect()
    rv, rs, _ = await client.mon_command(
        {
            "prefix": "osd erasure-code-profile set",
            "name": "chaos21",
            "profile": ["k=2", "m=1", "plugin=tpu"],
        }
    )
    assert rv == 0, rs
    await client.pool_create(
        "chaospool", "erasure", profile="chaos21", pg_num=cfg["pg_num"]
    )
    io = await client.open_ioctx("chaospool")

    # cluster-event timeline (ISSUE 16): every fault point the harness
    # arms ships an `audit` entry through the mon's LogMonitor, exactly
    # like an operator command — the end-of-run asserts reconstruct the
    # run's story from `log last` output alone
    armed_points: list[str] = []

    async def _audit_arm(point: str, detail: str) -> None:
        armed_points.append(point)
        await client.objecter.monc.send_log([{
            "prio": "info", "channel": "audit", "who": "client.chaos",
            "seq": len(armed_points), "stamp": time.time(),
            "msg": f"from='client.chaos' cmd=fault-arm point={point} "
                   f"{detail}: dispatch",
        }])

    async def arm_prob(point: str, one_in: int) -> None:
        inj.inject_probabilistic(point, one_in)
        await _audit_arm(point, f"one_in={one_in}")

    async def arm(point: str, err: int, hits: int) -> None:
        inj.inject(point, err, hits=hits)
        await _audit_arm(point, f"err={err} hits={hits}")

    expected: dict[str, bytes] = {}

    async def put(oid: str, nbytes: int) -> None:
        data = bytes(rng.getrandbits(8) for _ in range(nbytes))
        await io.write_full(oid, data)
        expected[oid] = data  # recorded only once the write was ACKED

    try:
        # ---- phase 0: baseline load -------------------------------------
        for i in range(cfg["objects"]):
            await put(f"base{i}", 8192 + 512 * (i % 5))
        report["events"].append("baseline written")

        # ---- phase 1: socket faults under load --------------------------
        await arm_prob("msgr.send", cfg["sock_one_in"])
        for i in range(cfg["objects"] // 2):
            await put(f"sock{i}", 8192)
            back = await io.read(f"base{i % cfg['objects']}")
            assert back == expected[f"base{i % cfg['objects']}"]
        inj.clear("msgr.send")
        report["events"].append("socket-fault load survived")

        # ---- phase 1.5: workload attribution + SLO + budgeted tracing ---
        # Mixed multi-pool load (EC chaospool + a replicated pool) with
        # ALWAYS-ON sampled tracing: head rate 1%, a token-bucket span
        # budget, and a forced complaint-age op proving tail always-keep.
        # Asserts the three ISSUE 10 promises at once: per-pool rates
        # and p99 attribute the load, the SLO burn rate stays under
        # bound while the cluster is healthy, and span retention honors
        # the budget WITHOUT losing the slow op's trace.
        await client.pool_create(
            "chaosrep", "replicated", size=min(2, cfg["osds"]),
            pg_num=cfg["pg_num"],
        )
        io_rep = await client.open_ioctx("chaosrep")
        expected_rep: dict[str, bytes] = {}
        for o in osds:
            o.conf.set("jaeger_tracing_enable", True)
            o.conf.set("op_trace_sample_rate", cfg["trace_sample_rate"])
            o.conf.set("op_trace_budget_per_sec", cfg["trace_budget"])
        sample_t0 = time.monotonic()
        for i in range(cfg["objects"]):
            await put(f"mix{i}", 8192)
            data = bytes(rng.getrandbits(8) for _ in range(4096))
            await io_rep.write_full(f"rep{i}", data)
            expected_rep[f"rep{i}"] = data
            back = await io.read(f"base{i % cfg['objects']}")
            assert back == expected[f"base{i % cfg['objects']}"]
            if i % 4 == 0:
                iostat_mod.tick()
        # force complaint-age ops: with the complaint window at zero,
        # every op finishing counts as slow — its trace must be KEPT
        # whatever the 1% head rate said
        for o in osds:
            o.op_tracker.complaint_time = 0.0
        await put("slowmix", 8192)
        await io_rep.write_full("repslow", b"s" * 4096)
        for o in osds:
            o.op_tracker.complaint_time = _osd_complaint_default()
        sample_elapsed = time.monotonic() - sample_t0
        iostat_mod.tick()
        for o in osds:
            o.conf.set("jaeger_tracing_enable", False)
            o.conf.set("op_trace_sample_rate", 1.0)
            o.conf.set("op_trace_budget_per_sec", 0.0)
        stats = [o.tracer.sampling_stats() for o in osds]
        agg = {
            k: sum(s[k] for s in stats)
            for k in ("sampled", "unsampled", "dropped_budget",
                      "kept_tail", "retained_spans")
        }
        report["trace_sampling"] = agg
        # retention within the token-bucket budget: head-sampled traces
        # are the budget-charged ones, bounded per daemon by refill
        # over the phase plus one burst
        budget_bound = len(osds) * (
            cfg["trace_budget"] * sample_elapsed + cfg["trace_budget"] + 1
        )
        assert agg["sampled"] <= budget_bound, (
            f"chaos: {agg['sampled']} head-sampled traces exceeded the "
            f"budget bound {budget_bound:.0f}"
        )
        assert agg["unsampled"] >= 1, (
            "chaos: a 1% sample rate under mixed load sampled everything"
            f" ({agg})"
        )
        assert agg["kept_tail"] >= 1, (
            f"chaos: complaint-age ops lost their traces to sampling ({agg})"
        )
        # SLO burn under bound while healthy + per-pool p99 attribution
        report["slo_worst_burn_rate"] = round(
            iostat_mod.worst_burn_rate("slow"), 3
        )
        assert report["slo_worst_burn_rate"] <= cfg["slo_burn_bound"], (
            f"chaos: SLO burn rate {report['slo_worst_burn_rate']} over "
            f"the {cfg['slo_burn_bound']} bound during mixed load"
        )
        iostat_view = iostat_mod.iostat()
        report["pool_p99_ms"] = {
            rec["pool"]: rec["p99_ms"] for rec in iostat_view.values()
        }
        assert any(
            rec["ops_total"] > 0 for rec in iostat_view.values()
        ), "chaos: iostat attributed no ops to any pool"
        report["events"].append(
            "mixed-load attribution + SLO + sampled tracing held"
        )

        # ---- phase 2: shard-read EIO burst ------------------------------
        # counted hits so the run converges deterministically: early reads
        # eat the errors (redundant-read escalation reconstructs where a
        # survivor set remains; a read whose EVERY shard answered EIO is
        # correctly failed to the client and retried), later reads run
        # clean as the hit budget drains
        await arm("ec.sub_read", 5, cfg["eio_hits"])
        eio_retries = 0
        for i in range(cfg["objects"] // 2):
            oid = f"base{i % cfg['objects']}"
            for _attempt in range(cfg["eio_hits"] + 2):
                try:
                    back = await io.read(oid)
                    break
                except Exception:
                    eio_retries += 1
            else:
                raise AssertionError(f"chaos: {oid} unreadable after EIO burst")
            assert back == expected[oid]
        inj.clear("ec.sub_read")
        report["eio_client_retries"] = eio_retries
        report["events"].append("EIO burst reconstructed")

        # ---- phase 2.5: gray OSD — hedged reads + laggy detection -------
        # The gray failure (ISSUE 17): one OSD heartbeats on time but
        # serves shard reads ~50x slow.  A healthy CONTROL window first
        # proves hedging is quiescent; then the victim's sub-reads are
        # delayed (delay_ms mode scoped to the victim daemon — its peers
        # stay fast), and the phase asserts the whole tolerance chain at
        # once: client read p99 stays UNDER the injected delay because
        # hedged/re-planned reads win, the victim — and ONLY the victim
        # — is detected laggy and surfaced as OSD_SLOW_PEER, the hedge
        # spend stays within the token-bucket budget, every read stays
        # byte-identical (no lost or doubled completions), and the laggy
        # state + health warn CLEAR once the delay lifts.
        from ceph_tpu.common.options import OPTIONS as _opts
        from ceph_tpu.osd.ec_backend import HEDGE_BURST
        from ceph_tpu.osd.pg_backend import shard_coll as _gray_coll

        chaos_primaries = [
            (o, pg)
            for o in osds
            if o._running
            for pg in o.pgs.values()
            if pg.pool.name == "chaospool" and pg.peering.is_primary()
        ]
        prim_count = {i: 0 for i in range(cfg["osds"])}
        for o, _pg in chaos_primaries:
            prim_count[o.whoami] += 1
        # a non-primary DATA-shard slot (acting[:k], k=2 for chaos21) is
        # where a gray peer actually hurts reads: normal whole-object
        # reads fetch exactly the k data shards
        data_member = {i: 0 for i in range(cfg["osds"])}
        for o, pg in chaos_primaries:
            for w in pg.acting()[:2]:
                if w != o.whoami:
                    data_member[w] += 1
        gray_id = min(
            (i for i in range(cfg["osds"]) if data_member[i] > 0),
            key=lambda i: (prim_count[i], -data_member[i], i),
        )
        gray_pgs = [
            (o, pg)
            for o, pg in chaos_primaries
            if o.whoami != gray_id and gray_id in pg.acting()[:2]
        ]
        assert gray_pgs, "chaos: gray victim serves no remote data shards"
        gray_oids = sorted(
            oid
            for o, pg in gray_pgs
            for oid in o.store.list_objects(
                _gray_coll(pg.pgid, pg.whoami_shard())
            )
            if oid in expected
        )[: 2 * cfg["objects"]]
        assert gray_oids, "chaos: no readable objects behind the gray victim"

        def _hedge_totals() -> dict[str, int]:
            return {
                k: sum(int(o.perf.get(k)) for o in osds if o._running)
                for k in ("ec_hedge_reads", "ec_hedge_wins",
                          "ec_hedge_denied")
            }

        hedge0 = _hedge_totals()
        for oid in gray_oids:  # control window: healthy reads
            assert await io.read(oid) == expected[oid]
        control = _hedge_totals()
        control_hedges = (
            control["ec_hedge_reads"] - hedge0["ec_hedge_reads"]
        )
        report["control_hedges"] = control_hedges
        assert control_hedges <= max(2, len(gray_oids) // 10), (
            f"chaos: healthy control window hedged {control_hedges} "
            f"times over {len(gray_oids)} reads"
        )
        # gray the victim: its sub-reads answer correctly but late
        inj.inject_delay(
            "ec.sub_read", cfg["gray_delay_ms"], who=f"osd.{gray_id}"
        )
        await _audit_arm(
            "ec.sub_read",
            f"delay_ms={cfg['gray_delay_ms']:.0f} who=osd.{gray_id}",
        )
        # priming reads: the first slow round trips are what the EWMA
        # laggy detector feeds on; reactive hedges keep even these fast
        # (the late losers land their RTT through the late-send ledger)
        for oid in gray_oids:
            assert await io.read(oid) == expected[oid]
        detectors = [
            o for o in osds if o._running and o.whoami != gray_id
        ]
        await _wait_until(
            lambda: any(gray_id in o.laggy_peers() for o in detectors),
            cfg["converge_timeout"],
            f"osd.{gray_id} to be detected laggy",
        )
        await _wait_until(
            lambda: gray_id in mons[0].osdmon.slow_peers(),
            cfg["converge_timeout"],
            "the mon to surface the laggy report",
        )
        slow = mons[0].osdmon.slow_peers()
        assert set(slow) == {gray_id}, (
            f"chaos: laggy detection fingered the wrong victim(s): "
            f"{sorted(slow)} (expected {{{gray_id}}})"
        )
        checks, _details = mons[0].health_checks()
        assert "OSD_SLOW_PEER" in checks, (
            f"chaos: no OSD_SLOW_PEER health warn ({sorted(checks)})"
        )
        assert f"osd.{gray_id}" in checks["OSD_SLOW_PEER"], (
            f"chaos: OSD_SLOW_PEER names the wrong victim: "
            f"{checks['OSD_SLOW_PEER']}"
        )
        # measured window: mixed load with the victim still gray — the
        # laggy deprioritization re-plans reads around it, so p99 must
        # land far under the injected delay
        gray_lat_s: list[float] = []
        for i in range(2 * len(gray_oids)):
            oid = gray_oids[i % len(gray_oids)]
            t0 = time.monotonic()
            back = await io.read(oid)
            gray_lat_s.append(time.monotonic() - t0)
            assert back == expected[oid], (
                f"chaos: {oid} corrupt while reading around the gray OSD"
            )
            if i % 4 == 0:
                await put(f"gray{i}", 8192)
        inj.clear("ec.sub_read")
        gray = _hedge_totals()
        gray_lat_s.sort()
        gray_p99_s = gray_lat_s[int(0.99 * (len(gray_lat_s) - 1))]
        gray_reads = len(gray_oids) + len(gray_lat_s)
        gray_hedges = (
            gray["ec_hedge_reads"] - control["ec_hedge_reads"]
        )
        report["gray_victim"] = gray_id
        report["gray_delay_ms"] = cfg["gray_delay_ms"]
        report["gray_reads"] = gray_reads
        report["gray_p99_ms"] = round(gray_p99_s * 1e3, 3)
        report["gray_hedges"] = gray_hedges
        report["gray_hedge_wins"] = (
            gray["ec_hedge_wins"] - hedge0["ec_hedge_wins"]
        )
        report["gray_hedge_denied"] = (
            gray["ec_hedge_denied"] - hedge0["ec_hedge_denied"]
        )
        report["hedge_rate"] = round(gray_hedges / max(1, gray_reads), 4)
        assert gray_hedges >= 1, "chaos: the gray window never hedged"
        assert report["gray_hedge_wins"] >= 1, (
            "chaos: no hedged read ever beat the gray straggler"
        )
        assert gray_p99_s * 1e3 <= cfg["gray_p99_bound_ms"], (
            f"chaos: gray-window read p99 {gray_p99_s * 1e3:.1f} ms "
            f"exceeded the {cfg['gray_p99_bound_ms']} ms bound (injected "
            f"delay {cfg['gray_delay_ms']:.0f} ms — hedging failed)"
        )
        # budget contract: spend is bounded by every primary backend's
        # burst plus the percent-of-subreads earn over the window
        # (k=2 sub-reads per read, plus the hedges themselves)
        pct = float(_opts["osd_ec_hedge_budget_percent"].default)
        budget_bound = HEDGE_BURST * len(chaos_primaries) + (
            pct / 100.0
        ) * (3 * gray_reads) + 1
        assert gray_hedges <= budget_bound, (
            f"chaos: {gray_hedges} hedges burst past the token-bucket "
            f"bound {budget_bound:.0f}"
        )
        # the delay lifted: laggy state and the health warn must CLEAR
        # (ping RTT keeps sampling the victim, decaying the EWMA through
        # the exit hysteresis; each reporter retracts, the mon retires)
        await _wait_until(
            lambda: all(
                gray_id not in o.laggy_peers() for o in detectors
            ),
            cfg["converge_timeout"],
            f"osd.{gray_id}'s laggy state to clear",
        )
        await _wait_until(
            lambda: "OSD_SLOW_PEER" not in mons[0].health_checks()[0],
            cfg["converge_timeout"], "OSD_SLOW_PEER to clear",
        )
        report["events"].append(
            f"gray osd.{gray_id} hedged around, detected laggy, cleared"
        )

        # ---- phase 3: device-launch faults -> host fallback -------------
        await arm("codec.launch", 5, cfg["launch_faults"])
        for i in range(cfg["objects"] // 2):
            await put(f"launch{i}", 2 * 8192)
        inj.clear("codec.launch")
        report["degraded_entered"] = bool(
            device_guard().degraded
            or ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"] > fallback0
        )
        report["events"].append("launch faults absorbed by host fallback")

        # ---- phase 3.5: deep scrub under load (ISSUE 9 QoS) -------------
        # Plant silent shard corruption, then deep-scrub every primary
        # WHILE client writes keep flowing.  The scrub's parity verify
        # rides aggregated compare-only launches on the background QoS
        # lane, so the phase proves three things at once: the corruption
        # is DETECTED (integrity), verify launches COALESCE (fewer
        # launches than objects scrubbed), and client write p99 stays
        # within the configured bound (QoS actually works — scrub never
        # starves the client lane).
        from ceph_tpu.os.transaction import Transaction
        from ceph_tpu.osd.pg_backend import shard_coll

        verify0 = ec_dispatch.VERIFY_LAUNCHES.snapshot()
        primaries = [
            (o, pg)
            for o in osds
            if o._running
            for pg in o.pgs.values()
            if pg.pool.name == "chaospool" and pg.peering.is_primary()
        ]
        # victim: the first object of the first primary PG that has one,
        # corrupted on a non-primary acting shard (the write path never
        # sees it; only deep scrub can)
        victim_oid = victim_pg = None
        for o, pg in primaries:
            coll = shard_coll(pg.pgid, pg.whoami_shard())
            oids = sorted(o.store.list_objects(coll))
            if oids:
                victim_oid, victim_pg = oids[0], pg
                break
        assert victim_oid is not None, "chaos: no scrubable objects"
        acting = victim_pg.acting()
        bad_shard = next(
            s for s, w in enumerate(acting) if w != victim_pg.whoami()
        )
        bad_osd = next(o for o in osds if o.whoami == acting[bad_shard])
        coll = shard_coll(victim_pg.pgid, bad_shard)
        good_bytes = bad_osd.store.read(coll, victim_oid, 0, 0)
        bad_osd.store.queue_transaction(
            Transaction().write(
                coll, victim_oid, 0,
                bytes([good_bytes[0] ^ 0xFF]) + good_bytes[1:],
            )
        )
        scrub_results: list = []
        pending_scrubs = 0
        for _o, pg in primaries:
            if pg.scrub(deep=True, on_done=scrub_results.append):
                pending_scrubs += 1
        # client load WHILE the scrub stream runs, per-op latency sampled
        scrub_lat_s: list[float] = []
        i = 0
        while len(scrub_results) < pending_scrubs:
            t0 = time.monotonic()
            await put(f"scrubload{i}", 8192)
            scrub_lat_s.append(time.monotonic() - t0)
            i += 1
            if i > 500:  # scrub wedged: fail via the wait below
                break
        await _wait_until(
            lambda: len(scrub_results) >= pending_scrubs,
            cfg["converge_timeout"], "deep scrubs under load to finish",
        )
        detected = any(
            victim_oid in res.inconsistent
            and acting[bad_shard] in res.inconsistent[victim_oid]
            for res in scrub_results
        )
        assert detected, "chaos: planted shard corruption not detected"
        vdelta = ec_dispatch.VERIFY_LAUNCHES.snapshot()
        v_launches = vdelta["launches"] - verify0["launches"]
        v_stripes = vdelta["stripes"] - verify0["stripes"]
        assert v_launches >= 1, "chaos: scrub never reached the verify kernel"
        objects_scrubbed = sum(r.objects_scrubbed for r in scrub_results)
        assert v_launches < max(2, objects_scrubbed), (
            "chaos: verify launches did not aggregate "
            f"({v_launches} launches for {objects_scrubbed} objects)"
        )
        scrub_lat_s.sort()
        scrub_p99 = (
            scrub_lat_s[int(0.99 * (len(scrub_lat_s) - 1))]
            if scrub_lat_s else 0.0
        )
        report["scrub_p99_ms"] = round(scrub_p99 * 1e3, 3)
        report["scrub_errors_detected"] = sum(r.errors for r in scrub_results)
        report["verify_launches"] = v_launches
        report["verify_stripes"] = v_stripes
        report["scrub_objects"] = objects_scrubbed
        assert scrub_p99 * 1e3 <= cfg["scrub_p99_bound_ms"], (
            f"chaos: client p99 {scrub_p99 * 1e3:.1f} ms exceeded the "
            f"{cfg['scrub_p99_bound_ms']} ms QoS bound under deep scrub"
        )
        # repair + rebuild so the run still converges damage-free: the
        # detected inconsistency raises PG_DAMAGED until the repair
        # scrub re-queues the shard and recovery rewrites it
        repair_done: list = []
        assert victim_pg.scrub(
            deep=True, repair=True, on_done=repair_done.append
        )
        await _wait_until(lambda: bool(repair_done), cfg["converge_timeout"],
                          "repair scrub to finish")
        await _wait_until(
            lambda: bad_osd.store.read(coll, victim_oid, 0, 0) == good_bytes,
            cfg["converge_timeout"], "repair to rewrite the corrupt shard",
        )
        report["events"].append("deep scrub under load detected + repaired")

        # ---- phase 3.7: pipelined wedge (ISSUE 11) ----------------------
        # Launch faults armed while depth>1 launches are IN FLIGHT: a
        # wedge at pipeline depth must host-fallback every affected
        # ticket byte-identically WITHOUT losing the other in-flight
        # groups' tickets, and the donation pool's per-slot refcounts
        # must never recycle a live buffer (the invariant gauge stays
        # 0).  The live OSDs' aggregators get the depth through the
        # runtime config observer — the knob path itself is under test.
        import numpy as np

        from ceph_tpu.codec.matrix_codec import EncodeAggregator
        from ceph_tpu.codec.registry import instance as codec_registry

        for o in osds:
            if o._running:
                o.conf.set("ec_tpu_pipeline_depth", 3)
        pipe0 = ec_dispatch.PIPELINE.snapshot()
        ec42 = codec_registry().factory("tpu", {"k": "4", "m": "2"})
        pagg = EncodeAggregator(window=2, pipeline_depth=2)
        nrng = np.random.default_rng(cfg["seed"] ^ 0x11)
        batches = [
            nrng.integers(0, 256, (2, 4, 4096), dtype=np.uint8)
            for _ in range(8)
        ]
        await arm("codec.launch", 5, 2)
        tickets = [pagg.submit(ec42, b) for b in batches]
        inj.clear("codec.launch")
        pagg.flush()
        wedge_identical = all(
            np.array_equal(
                np.asarray(t), np.asarray(ec42.encode_array_host(b))
            )
            for t, b in zip(tickets, batches)
        )
        assert wedge_identical, (
            "chaos: pipelined-wedge tickets diverged from the host oracle"
        )
        pipe1 = ec_dispatch.PIPELINE.snapshot()
        max_depth = max(
            (
                r.get("inflight_depth", 0)
                for r in flight_recorder().records()
                if r["kind"] == "encode"
            ),
            default=0,
        )
        assert max_depth >= 2, (
            f"chaos: pipelined wedge never reached depth>1 ({max_depth})"
        )
        recycled = (
            pipe1["donation_recycled_live"]
            - pipe0["donation_recycled_live"]
        )
        assert recycled == 0, (
            f"chaos: donation pool recycled {recycled} LIVE buffer(s)"
        )
        report["pipeline_wedge_tickets"] = len(tickets)
        report["pipeline_max_inflight_depth"] = max_depth
        report["pipeline_drains"] = pipe1["drains"] - pipe0["drains"]
        report["donation_recycled_live"] = recycled
        report["events"].append("pipelined wedge recovered byte-identical")

        # ---- phase 3.8: offload fallback — csum + compressor (ISSUE 20) -
        # Launch faults armed while the NON-EC offload services' launches
        # are in flight: the device crc32c service (BlueStore per-block
        # checksums, switched on live through the bluestore_csum_offload
        # observer — the knob path itself is under test) and the batched
        # device compressor.  Every affected launch must host-fallback
        # byte-identically — the csum oracle IS utils/crc32c and the
        # compressor's host transform is the device transform's twin —
        # so the phase proves it three ways at once: directly-submitted
        # tickets match the host oracle, the csums BlueStore actually
        # STORED under fire equal crc32c of the stored form, and
        # compressed blobs round-trip.  The offload_inflight mempool
        # must drain to zero (EC-fusion tickets whose transactions were
        # wire-encoded are never consumed — the drain is what settles
        # them), and client p99 stays bounded while the faults land.
        from ceph_tpu.compressor import get_compressor
        from ceph_tpu.ops.checksum_offload import (
            crc32c_host_rows,
            default_csum_aggregator,
        )
        from ceph_tpu.compressor.device import default_compress_aggregator
        from ceph_tpu.ops.offload_runtime import offload_perf_dump
        from ceph_tpu.os.bluestore import BLOCK as BS_BLOCK
        from ceph_tpu.utils.crc32c import crc32c as host_crc32c

        off0 = offload_perf_dump()
        for o in osds:
            if o._running:
                o.conf.set("bluestore_csum_offload", True)
        csum_agg = default_csum_aggregator()
        crng = np.random.default_rng(cfg["seed"] ^ 0x20)
        csum_batches = [
            crng.integers(0, 256, (8, BS_BLOCK), dtype=np.uint8)
            for _ in range(4)
        ]
        dev_comp = get_compressor("device")
        comp_blocks = []
        for i in range(12):  # zero-heavy: the elision path really elides
            buf = bytearray(BS_BLOCK)
            buf[64 * (i % 8): 64 * (i % 8) + 16] = bytes(range(16))
            buf[0] = i + 1
            comp_blocks.append(bytes(buf))
        await arm("codec.launch", 5, 2 + cfg["launch_faults"])
        csum_tickets = [csum_agg.submit_blocks(b) for b in csum_batches]
        comp_blobs = dev_comp.compress_batch(comp_blocks)
        assert all(
            dev_comp.decompress(blob) == blk
            for blob, blk in zip(comp_blobs, comp_blocks)
        ), "chaos: wedged compressor blobs did not round-trip"
        assert all(
            blob == dev_comp.compress(blk)
            for blob, blk in zip(comp_blobs, comp_blocks)
        ), "chaos: wedged compressor blobs diverged from the host form"
        assert all(
            np.array_equal(
                np.asarray(t.result()), crc32c_host_rows(b)
            )
            for t, b in zip(csum_tickets, csum_batches)
        ), "chaos: wedged csum tickets diverged from utils/crc32c"
        # mixed client load while the remaining armed hits land on the
        # write path's OWN csum launches (and the read-backs' verify
        # recomputes), per-op latency sampled for the p99 bound
        off_lat_s: list[float] = []
        for i in range(cfg["objects"]):
            t0 = time.monotonic()
            await put(f"offload{i}", 8 * BS_BLOCK)
            off_lat_s.append(time.monotonic() - t0)
            back = await io.read(f"offload{i}")
            assert back == expected[f"offload{i}"], (
                f"chaos: offload{i} corrupt under csum-offload faults"
            )
        inj.clear("codec.launch")
        # the csums BlueStore STORED under fire are the host oracle's:
        # walk every live store's offload-phase blocks and recompute
        checked_blocks = 0
        for o in osds:
            if not o._running:
                continue
            st = o.store
            for coll in sorted(st._colls):
                for oid in sorted(st.list_objects(coll)):
                    if not str(oid).startswith("offload"):
                        continue
                    on = st._get_onode(coll, oid)
                    for bidx in sorted(on.blocks):
                        poff, crc, clen = on.blocks[bidx]
                        stored = st._staged.get(poff)
                        if stored is None:
                            stored = st._block_read(
                                poff, clen if clen else BS_BLOCK
                            )
                        if not clen:
                            stored = stored.ljust(BS_BLOCK, b"\x00")
                        assert host_crc32c(stored) == crc, (
                            f"chaos: stored csum for {coll}/{oid} block "
                            f"{bidx} is not utils/crc32c of the stored "
                            "form — the fallback was not byte-identical"
                        )
                        checked_blocks += 1
        assert checked_blocks >= 8, (
            f"chaos: offload phase verified only {checked_blocks} stored "
            "blocks — the load never reached the csum-offload write path"
        )
        # settle the never-consumed EC-fusion tickets, then the
        # offload_inflight pool must hold ZERO bytes
        csum_agg.drain()
        default_compress_aggregator().drain()
        offload_leaked = hbm.current_bytes("offload_inflight")
        off1 = offload_perf_dump()
        for o in osds:
            if o._running:
                o.conf.set("bluestore_csum_offload", False)
        off_lat_s.sort()
        off_p99_s = (
            off_lat_s[int(0.99 * (len(off_lat_s) - 1))]
            if off_lat_s else 0.0
        )
        report["offload_csum_launches"] = (
            off1.get("csum.launches", 0) - off0.get("csum.launches", 0)
        )
        report["offload_csum_fallbacks"] = (
            off1.get("csum.host_fallbacks", 0)
            - off0.get("csum.host_fallbacks", 0)
        )
        report["offload_compress_fallbacks"] = (
            off1.get("compress.host_fallbacks", 0)
            - off0.get("compress.host_fallbacks", 0)
        )
        report["offload_stored_blocks"] = checked_blocks
        report["offload_leaked_bytes"] = offload_leaked
        report["offload_p99_ms"] = round(off_p99_s * 1e3, 3)
        assert report["offload_csum_launches"] >= 1, (
            "chaos: the csum service never launched under the offload load"
        )
        assert report["offload_csum_fallbacks"] >= 1, (
            "chaos: armed launch faults never drove a csum host fallback"
        )
        assert report["offload_compress_fallbacks"] >= 1, (
            "chaos: armed launch faults never drove a compress host "
            "fallback"
        )
        assert offload_leaked == 0, (
            f"chaos: {offload_leaked} offload_inflight bytes leaked "
            f"after drain (reconcile: {hbm.reconcile()})"
        )
        assert off_p99_s * 1e3 <= cfg["offload_p99_bound_ms"], (
            f"chaos: client p99 {off_p99_s * 1e3:.1f} ms exceeded the "
            f"{cfg['offload_p99_bound_ms']} ms bound under offload faults"
        )
        report["events"].append("offload faults host-fallback byte-identical")

        # ---- phase 4: OSD flap + recovery -------------------------------
        victim_id = rng.randrange(cfg["osds"])
        victim = osds[victim_id]
        victim_store = victim.store
        await victim.stop()
        await _wait_until(
            lambda: not mons[0].osdmon.osdmap.is_up(victim_id),
            10.0,
            f"mon marking osd.{victim_id} down",
        )
        for i in range(cfg["objects"] // 2):
            await put(f"flap{i}", 8192)  # degraded writes
            oid = f"base{i % cfg['objects']}"
            assert await io.read(oid) == expected[oid]  # degraded reads
        revived = OSD(victim_id, monmap, conf=_osd_conf(victim_id),
                      store=victim_store)
        await revived.start()
        await revived.wait_for_up()
        osds[victim_id] = revived
        report["events"].append(f"osd.{victim_id} flapped")

        # ---- phase 5: whole-OSD recovery storm (ISSUE 15) ----------------
        # A victim dies for good.  The mon's (dampened) down->out sweep
        # outs it — first markdown, so the base grace applies — CRUSH
        # fills its slots in place from the standing membership (the
        # cluster runs k+m+1 OSDs, so indep placement has a spare to
        # pull into each hole without disturbing survivor positions),
        # and every surviving primary's recovery-storm controller
        # batches the flooded missing sets into cross-PG decode waves
        # WHILE mixed client load keeps flowing.  Recovery-path wedges
        # (ec.recover_push, peering.msg) are armed mid-storm so the
        # stalled-push retry and the peering re-kick self-heal under
        # fire.  Asserts the ISSUE 15 acceptance: rebuild-time bound
        # AND client-p99 bound simultaneously, decode launches <
        # objects recovered (wave batching witnessed by flight
        # records), and the whole-OSD bar was visible.
        def _primaries_clean() -> bool:
            return all(
                pg.is_clean
                for o in osds
                if o._running
                for pg in o.pgs.values()
                if pg.peering.is_primary()
            )

        # let the phase-4 flap's recovery settle before the kill so the
        # storm phase measures the FAILURE rebuild alone
        await _wait_until(_primaries_clean, cfg["converge_timeout"],
                          "pre-storm churn to settle")
        # arm the mon's down->out sweep NOW (it was off so the earlier
        # flap could never race an auto-out); from here on a dead OSD's
        # data is remapped after the (dampened) grace
        mons[0].conf.set(
            "mon_osd_down_out_interval", cfg["down_out_interval"]
        )
        def _ec_pgs_holding(osd_id: int) -> int:
            osdmap = mons[0].osdmon.osdmap
            ec_pool = osdmap.pools[osdmap.pool_name_to_id["chaospool"]]
            n = 0
            for ps in range(ec_pool.pg_num):
                _u, _up, acting, _p = osdmap.pg_to_up_acting_osds(
                    ec_pool.id, ps
                )
                if osd_id in acting:
                    n += 1
            return n

        # the storm victim: an original OSD (not the phase-4 flapper,
        # whose markdown history would dampen the auto-out) holding the
        # most EC shards, so the kill floods the widest missing set
        candidates = [i for i in range(cfg["osds"]) if i != victim_id]
        storm_victim_id = max(candidates, key=_ec_pgs_holding)
        assert _ec_pgs_holding(storm_victim_id) >= 1, (
            "chaos: no storm victim holds chaospool shards"
        )
        storm_victim = osds[storm_victim_id]
        decode_storm0 = ec_dispatch.DECODE_LAUNCHES.snapshot()
        # baselines over the SURVIVOR set: the victim's counters leave
        # the final sum with it, so including them here would undercount
        # the delta (earlier phases can legitimately engage storms)
        storm_objs0 = sum(
            o.recovery_storm.objects_admitted
            for o in osds
            if o._running and o.whoami != storm_victim_id
        )
        storm_waves0 = sum(
            o.recovery_storm.waves
            for o in osds
            if o._running and o.whoami != storm_victim_id
        )
        wave_recs0 = sum(
            1 for r in flight_recorder().records()
            if r["kind"] == "recovery_wave"
        )
        await storm_victim.stop()
        await arm("ec.recover_push", 5, 2)
        await arm("peering.msg", 5, 2)
        await _wait_until(
            lambda: not mons[0].osdmon.osdmap.is_up(storm_victim_id),
            10.0, f"mon marking osd.{storm_victim_id} down",
        )
        await _wait_until(
            lambda: not mons[0].osdmon.osdmap.osds[storm_victim_id].in_,
            max(20.0, 10 * cfg["down_out_interval"]),
            f"auto-out of dead osd.{storm_victim_id}",
        )
        t_out = time.monotonic()
        await _wait_until(
            lambda: not _primaries_clean(), 10.0,
            "the storm's re-peer/missing flood to become visible",
        )
        # mixed load WHILE the rebuild storms, per-op latency sampled
        # for the simultaneous client-p99 bound
        storm_lat_s: list[float] = []
        i = 0
        while not _primaries_clean() and i < 400:
            t0 = time.monotonic()
            await put(f"storm{i}", 8192)
            storm_lat_s.append(time.monotonic() - t0)
            oid = f"base{i % cfg['objects']}"
            back = await io.read(oid)
            assert back == expected[oid], f"chaos: {oid} lost mid-storm"
            i += 1
        await _wait_until(_primaries_clean, cfg["converge_timeout"],
                          "whole-OSD rebuild to complete")
        rebuild_seconds = time.monotonic() - t_out
        inj.clear("ec.recover_push")
        inj.clear("peering.msg")
        live = [o for o in osds if o._running]
        dec_storm = ec_dispatch.DECODE_LAUNCHES.snapshot()
        storm_launches = dec_storm["launches"] - decode_storm0["launches"]
        storm_objects = sum(
            o.recovery_storm.objects_admitted for o in live
        ) - storm_objs0
        storm_waves = sum(o.recovery_storm.waves for o in live) - storm_waves0
        wave_recs = sum(
            1 for r in flight_recorder().records()
            if r["kind"] == "recovery_wave"
        ) - wave_recs0
        push_retries = sum(
            getattr(pg.backend, "push_retries", 0)
            for o in live
            for pg in o.pgs.values()
        )
        storm_lat_s.sort()
        storm_p99 = (
            storm_lat_s[int(0.99 * (len(storm_lat_s) - 1))]
            if storm_lat_s else 0.0
        )
        report["rebuild_seconds"] = round(rebuild_seconds, 3)
        report["storm_p99_ms"] = round(storm_p99 * 1e3, 3)
        report["storm_waves"] = storm_waves
        report["storm_objects"] = storm_objects
        report["storm_decode_launches"] = storm_launches
        report["storm_wave_flight_records"] = wave_recs
        report["storm_push_retries"] = push_retries
        assert storm_waves >= 1, "chaos: no recovery-storm wave launched"
        assert wave_recs >= 1, (
            "chaos: storm waves left no flight records"
        )
        assert storm_objects >= 5, (
            f"chaos: storm recovered too few objects ({storm_objects}) "
            "to witness wave batching"
        )
        assert storm_launches < storm_objects, (
            f"chaos: decode launches ({storm_launches}) did not batch "
            f"below objects recovered ({storm_objects})"
        )
        assert rebuild_seconds <= cfg["storm_rebuild_bound_sec"], (
            f"chaos: whole-OSD rebuild took {rebuild_seconds:.1f}s, over "
            f"the {cfg['storm_rebuild_bound_sec']}s bound"
        )
        assert storm_p99 * 1e3 <= cfg["storm_p99_bound_ms"], (
            f"chaos: client p99 {storm_p99 * 1e3:.1f} ms exceeded the "
            f"{cfg['storm_p99_bound_ms']} ms bound during the storm"
        )
        # the storm victim stays dead+out: this framework keeps PG
        # logs/infos in memory, so a revived-after-reshuffle OSD would
        # rejoin with no interval history (the one case stray-shard
        # redirection cannot source) — the cluster runs k+m+2 OSDs so
        # both failure phases rebuild onto standing capacity instead
        report["events"].append("whole-OSD storm rebuilt under load")

        # ---- phase 6: flapping OSD vs mon dampening ----------------------
        # Rapid stop/start bounces accumulate markdowns; the dampened
        # down->out grace grows exponentially, so the map stays stable
        # (ZERO auto-outs) through the flaps — then the same OSD dies
        # for real, and the sweep still outs it past the (longer)
        # grace, proving dampening delays churn without ever orphaning
        # a genuinely dead OSD's data.
        auto_outs0 = mons[0].osdmon.flap_stats()["auto_outs_total"]
        flapper_id = max(
            (
                i for i in range(cfg["osds"])
                if i not in (victim_id, storm_victim_id)
            ),
            key=_ec_pgs_holding,
        )
        for _cycle in range(2):
            flapper = osds[flapper_id]
            fstore = flapper.store
            await flapper.stop()
            await _wait_until(
                lambda: not mons[0].osdmon.osdmap.is_up(flapper_id),
                10.0, f"mon marking flapping osd.{flapper_id} down",
            )
            flapper = OSD(flapper_id, monmap, conf=_osd_conf(flapper_id),
                          store=fstore)
            await flapper.start()
            await flapper.wait_for_up()
            osds[flapper_id] = flapper
        stats = mons[0].osdmon.flap_stats()
        report["flap_auto_outs"] = (
            stats["auto_outs_total"] - auto_outs0
        )
        fl = stats["osds"].get(flapper_id, {})
        report["flap_markdowns"] = fl.get("markdowns", 0)
        report["flap_grace_sec"] = fl.get("grace_sec", 0.0)
        assert report["flap_auto_outs"] == 0, (
            f"chaos: dampening failed — {report['flap_auto_outs']} "
            "auto-out(s) during the flap bounces"
        )
        assert report["flap_markdowns"] >= 2, (
            f"chaos: flap history lost ({report['flap_markdowns']})"
        )
        assert report["flap_grace_sec"] >= 2 * cfg["down_out_interval"], (
            f"chaos: dampened grace {report['flap_grace_sec']}s did not "
            f"grow past 2x the {cfg['down_out_interval']}s base"
        )
        # the genuinely dead case: the flapper dies for good — outed
        # past the dampened grace, and its data still rebuilds
        dead = osds[flapper_id]
        await dead.stop()
        await _wait_until(
            lambda: not mons[0].osdmon.osdmap.is_up(flapper_id),
            10.0, f"mon marking dead osd.{flapper_id} down",
        )
        t_dead = time.monotonic()
        await _wait_until(
            lambda: not mons[0].osdmon.osdmap.osds[flapper_id].in_,
            max(30.0, 20 * cfg["down_out_interval"]),
            "dead flapper's dampened auto-out",
        )
        dead_out_wait = time.monotonic() - t_dead
        report["flap_dead_out_wait_sec"] = round(dead_out_wait, 3)
        report["flap_dampened_holds"] = (
            mons[0].osdmon.flap_stats()["dampened_holds"]
        )
        assert dead_out_wait >= 1.5 * cfg["down_out_interval"], (
            f"chaos: dead flapper outed after {dead_out_wait:.1f}s — the "
            "dampened grace never applied"
        )
        await _wait_until(_primaries_clean, cfg["converge_timeout"],
                          "dead flapper's data to rebuild")
        report["events"].append("flap dampening held; dead OSD rebuilt")

        # ---- convergence ------------------------------------------------
        def all_clean() -> bool:
            # PG.progress_active() is the READ-ONLY predicate:
            # progress_status()'s episode bookkeeping belongs to the
            # OSD's own status reports, not a monitoring poll.  A SET of
            # distinct PGs (not a per-poll tally) so the reported count
            # is a property of the run, not of the poll frequency.
            progress_pgs_seen.update(
                (o.whoami, pg.pool.id, pg.ps)
                for o in osds
                if o._running
                for pg in o.pgs.values()
                if pg.progress_active()
            )
            return all(
                pg.is_clean
                for o in osds
                if o._running
                for pg in o.pgs.values()
                if pg.peering.is_primary()
            )

        await _wait_until(all_clean, cfg["converge_timeout"],
                          "all PGs active+clean")
        # the device guard must have healed (probe) by convergence time
        await _wait_until(
            lambda: not device_guard().degraded, 10.0,
            "device backend re-probe self-heal",
        )
        # health clear: no stuck SLOW_OPS, no TPU_BACKEND_DEGRADED
        def health_clear() -> bool:
            checks, _ = mons[0].health_checks()
            return (
                "SLOW_OPS" not in checks
                and "TPU_BACKEND_DEGRADED" not in checks
            )

        await _wait_until(health_clear, cfg["converge_timeout"],
                          "health clear of SLOW_OPS/TPU_BACKEND_DEGRADED")

        # ---- zero lost writes -------------------------------------------
        lost = 0
        for oid, data in expected.items():
            if await io.read(oid) != data:
                lost += 1
        for oid, data in expected_rep.items():
            if await io_rep.read(oid) != data:
                lost += 1
        report["lost_writes"] = lost
        report["converged"] = lost == 0

        # ---- metrics (the PR-1 histogram substrate) ---------------------
        live = [o for o in osds if o._running]
        p99 = [
            _p99_from_histogram(o.perf.dump_histograms().get("op_latency"))
            for o in live
        ]
        # the tracked-metric aliases ROADMAP item 4 promotes into the
        # bench trajectory (PROGRESS/bench reporting reads these keys):
        # p99 in milliseconds, and recovery-launch occupancy = mean
        # stripes per aggregated decode launch (1.0 = no aggregation
        # benefit, higher = recovery coalesced).  A p99 in the
        # histogram's +Inf overflow bucket reports as null — json.dumps
        # would otherwise emit the non-RFC `Infinity` token and poison
        # every strict consumer of the --out file / bench fold.
        p99_max = max(p99) if p99 else 0.0
        if p99_max == float("inf"):
            report["p99_op_latency_sec"] = None
            report["chaos_p99_ms"] = None
        else:
            report["p99_op_latency_sec"] = p99_max
            report["chaos_p99_ms"] = round(p99_max * 1e3, 3)
        dec = ec_dispatch.DECODE_LAUNCHES.snapshot()
        d_launches = dec["launches"] - decode0["launches"]
        d_stripes = dec["stripes"] - decode0["stripes"]
        report["recovery_occupancy"] = round(
            d_stripes / d_launches, 3
        ) if d_launches else 0.0
        occ = [
            o.decode_aggregator.perf.get("launches") for o in live
        ]
        report["recovery_decode_launches"] = int(sum(occ))
        report["progress_events_seen"] = len(progress_pgs_seen)
        # flight-recorder summary (ISSUE 8): launches, mean queue-wait,
        # device occupancy over the chaos run (the recorder was reset at
        # run start, so these are run-relative)
        report["flight"] = flight_recorder().summary()
        report["fallback_launches"] = (
            ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"] - fallback0
        )
        # HBM ledger verdict (ISSUE 13): peak residency during the run
        # (the headroom number bench rounds correlate against) and ZERO
        # leaked bytes once the EC pipelines drain — host-fallback and
        # sticky-error launches released their holds too, or this
        # assertion names the bytes they kept
        from ceph_tpu.codec.matrix_codec import drain_all_aggregators

        drain_all_aggregators()
        report["hbm_peak_bytes"] = hbm.peak_total_bytes()
        hbm_leaked = (
            hbm.current_bytes("ec_pipeline_inflight")
            + hbm.current_bytes("verify")
            + hbm.current_bytes("offload_inflight")
        )
        report["hbm_leaked_bytes"] = hbm_leaked
        assert hbm_leaked == 0, (
            f"chaos: {hbm_leaked} HBM bytes leaked after drain "
            f"(reconcile: {hbm.reconcile()})"
        )
        report["msgr_resends"] = sum(
            o.msgr.resends + o.monc.msgr.resends for o in live
        ) + client.objecter.msgr.resends
        report["op_resends"] = int(client.objecter.perf.get("op_resend"))
        # trend-sentinel verdict (ISSUE 14): a healthy converged run
        # must not have fired TPU_THROUGHPUT_REGRESSION /
        # TPU_OCCUPANCY_COLLAPSE / TPU_QUEUE_WAIT_INFLATION — the
        # module sampled real MMgrReports the whole run with windows
        # short enough to actually evaluate (pinned above)
        report["history_sentinels_fired"] = history_mod.sentinels_fired
        report["history_sentinels_active"] = sorted(history_mod.sentinels)
        report["history_store"] = history_mod.store.stats()
        assert report["history_sentinels_fired"] == 0, (
            f"chaos: trend sentinels fired on a healthy run: "
            f"{report['history_sentinels_active']} "
            f"(fired {report['history_sentinels_fired']})"
        )
        # the final snapshot re-waits health_clear: the metrics section
        # above takes long enough for one stale beacon (e.g. a status
        # blob sampled mid-probe) to transiently re-raise a check the
        # run already proved clear — capture a settled view, not a race
        await _wait_until(health_clear, 10.0,
                          "health to settle for the final snapshot")
        report["health_checks"] = mons[0].health_checks()[0]

        # ---- cluster-event timeline (ISSUE 16) --------------------------
        # The run's story must be reconstructable from `log last` output
        # ALONE: pull the committed tail once, then derive every verdict
        # below from that single payload — no daemon introspection.
        rv, rs, out = await client.mon_command(
            {"prefix": "log last", "num": 1000}, timeout=10.0
        )
        assert rv == 0, f"chaos: log last failed: {rs}"
        clog_tail = json.loads(out)["entries"]
        rv, _, out = await client.mon_command(
            {"prefix": "log last", "num": 1000, "channel": "audit"},
            timeout=10.0,
        )
        assert rv == 0
        audit_tail = json.loads(out)["entries"]
        report["clog_entries"] = len(clog_tail)
        err_entries = [e for e in clog_tail if e.get("prio") == "error"]
        report["clog_errors"] = len(err_entries)
        # a healthy converged run carries NO error entries beyond the
        # ones the harness deliberately caused: the planted scrub
        # corruption (including the OSD_SCRUB_ERRORS health raise, when
        # the mon tick catches it before the repair clears it) and the
        # armed fault points.  A repeat-dedup marker inherits the
        # collapsed entry's prio, so an error-level "last message
        # repeated" stands for an already-allowed error.
        expected_err = ("inconsistent", "crc mismatch", "recovery of",
                        "backfill push", "RMW read", "encode launch",
                        "scrub errors", "last message repeated")
        unexpected = [
            e["msg"] for e in err_entries
            if not any(pat in e["msg"] for pat in expected_err)
        ]
        assert not unexpected, (
            f"chaos: unexpected ERR cluster-log entries: {unexpected}"
        )
        # every armed fault point produced an audit entry, and so did
        # the run's mutating mon commands (profile/pool creation)
        assert all(e.get("channel") == "audit" for e in audit_tail), (
            "chaos: `log last channel=audit` returned non-audit entries"
        )
        audit_msgs = [e["msg"] for e in audit_tail]
        for point in sorted(set(armed_points)):
            assert any(f"point={point}" in m for m in audit_msgs), (
                f"chaos: armed fault point {point} left no audit entry"
            )
        assert any("osd pool create" in m for m in audit_msgs), (
            "chaos: pool creation left no audit entry"
        )
        report["audit_entries"] = len(audit_tail)

        # storm-phase reconstruction: the ordered milestone subsequence
        # (down -> out -> engage -> wave -> complete for the storm
        # victim; down -> dampened hold -> out for the dead flapper)
        # must read straight out of the committed log, in order
        def _subsequence(entries, milestones, start=0):
            found, pos = [], start
            for label, pat in milestones:
                idx = next(
                    (j for j in range(pos, len(entries))
                     if pat in entries[j]["msg"]),
                    -1,
                )
                assert idx >= 0, (
                    f"chaos: timeline milestone {label!r} ({pat!r}) "
                    f"missing from the cluster log after index {pos}"
                )
                found.append(label)
                pos = idx + 1
            return found

        storm_timeline = _subsequence(clog_tail, [
            ("down", f"osd.{storm_victim_id} marked down"),
            ("out", f"osd.{storm_victim_id} marked out"),
            ("storm_engaged", "recovery storm ENGAGED"),
            ("wave", "recovery storm wave"),
            ("storm_complete", "recovery storm complete"),
        ])
        # the dead flapper's final down is its LAST markdown entry; the
        # dampened hold ("osd.N down Xs; auto-out deferred ...") and the
        # auto-out must follow it
        last_down = max(
            j for j, e in enumerate(clog_tail)
            if f"osd.{flapper_id} marked down" in e["msg"]
        )
        flap_timeline = ["down"] + _subsequence(clog_tail, [
            ("dampened", f"osd.{flapper_id} down"),
            ("out", f"osd.{flapper_id} marked out"),
        ], start=last_down + 1)
        report["storm_timeline"] = storm_timeline
        report["flap_timeline"] = flap_timeline
        report["events"].append("timeline reconstructed from cluster log")
        # lock-order verdict (ISSUE 12 tracked keys): zero violations is
        # part of convergence, and the observed ordering graph rides the
        # JSON so a run's lock hierarchy is inspectable after the fact
        report["lockdep_violations"] = (
            lockdep.violations() - lockdep_violations0
        )
        report["lockdep_graph"] = lockdep.graph_dump()
        assert report["lockdep_violations"] == 0, (
            f"lock-order violations during the chaos run: "
            f"{report['lockdep_violations']} (graph: "
            f"{report['lockdep_graph']})"
        )
        # round-over-round gating (ISSUE 14): fold the perf_compare
        # regressions slice against the committed BENCH_r*.json corpus
        # (chaos keys ride the bench rounds' `chaos` sub-object), so
        # the chaos trajectory is judged like the throughput one.
        # Guarded: a converged report must survive a compare fault.
        try:
            from ceph_tpu.tools.perf_compare import compare_round

            report["regressions"] = compare_round({"chaos": report})
        except Exception as e:
            from ceph_tpu.common.log import dout

            dout("chaos", 1, f"perf-compare fold failed: {e!r}")
            report["regressions"] = {"error": repr(e)}
    finally:
        inj.clear()
        device_guard().mark_healthy()
        # the pipelined-wedge phase raised the process-wide default
        # aggregators' depth through the OSD observers — restore the
        # option default so an embedded run (the tier-1 smoke inside a
        # shared pytest process) leaves no config behind
        from ceph_tpu.codec.matrix_codec import (
            default_decode_aggregator,
            default_encode_aggregator,
            default_verify_aggregator,
        )
        from ceph_tpu.common.options import OPTIONS

        depth_default = int(OPTIONS["ec_tpu_pipeline_depth"].default)
        for agg in (default_encode_aggregator(), default_decode_aggregator(),
                    default_verify_aggregator()):
            agg.configure(pipeline_depth=depth_default)
        await client.shutdown()
        await mgr.stop()
        for o in osds:
            if o._running:
                await o.stop()
        for m in mons:
            await m.stop()
        await asyncio.sleep(0.05)
    return report


def run_chaos(
    seed: int = 0xC405,
    smoke: bool = False,
    osds: int = 5,
    objects: int = 24,
    pg_num: int = 4,
) -> dict:
    """Run the harness to completion and return the report dict.  Raises
    (TimeoutError / AssertionError) when the cluster fails to converge —
    convergence IS the assertion."""
    if smoke:
        # fast, seed-fixed tier-1 variant: small but still crossing every
        # phase (sockets, EIO, launch faults, flap + recovery, whole-OSD
        # storm + flap dampening).  k+m+2 OSDs: BOTH failure phases
        # (storm victim, dead flapper) leave their OSD out for good and
        # rebuild onto standing capacity — CRUSH fills the holes from
        # the known membership, and the stray-shard redirection covers
        # the slot reshuffles the fill can cause.
        osds, objects, pg_num = 5, 8, 4
    cfg = {
        "seed": seed,
        "smoke": smoke,
        "osds": osds,
        "objects": objects,
        "pg_num": pg_num,
        "sock_one_in": 25,
        "eio_hits": 3 if smoke else 8,
        "launch_faults": 2 if smoke else 4,
        "converge_timeout": 30.0 if smoke else 90.0,
        # client write p99 bound while the deep-scrub verify stream runs
        # (the QoS acceptance gate).  Deliberately generous for shared
        # CI hosts — the assertion exists to catch scrub BLOCKING the
        # client lane (seconds-scale stalls), not to benchmark
        "scrub_p99_bound_ms": 2000.0 if smoke else 1000.0,
        # ISSUE 10 mixed-load gates: the pool latency SLO target (same
        # generosity rationale as the scrub bound — the burn-rate
        # assertion catches seconds-scale starvation, not CI noise),
        # the burn bound the mixed phase must stay under, and the
        # always-on trace sampling knobs (1% head rate + span budget)
        "slo_target_ms": 2000.0 if smoke else 1000.0,
        "slo_burn_bound": 1.0,
        "trace_sample_rate": 0.01,
        "trace_budget": 10.0,
        # ISSUE 15 storm/flap gates: the mon's down->out base interval
        # (kept small so the auto-out and the dampened grace both land
        # inside the run), the whole-OSD rebuild-time bound, and the
        # client p99 bound enforced SIMULTANEOUSLY with it.  Bounds are
        # generous for shared CI hosts — they catch a rebuild that
        # stalls or starves clients for seconds, not noise.
        "down_out_interval": 2.0 if smoke else 5.0,
        "storm_rebuild_bound_sec": 30.0 if smoke else 60.0,
        "storm_p99_bound_ms": 2000.0 if smoke else 1000.0,
        # ISSUE 17 gray-OSD gates: the injected sub-read delay (the
        # "~50x" gray multiplier against millisecond-scale healthy
        # reads) and the client read-p99 bound the hedged/re-planned
        # reads must beat.  The bound sits DELIBERATELY under the delay:
        # if hedging fails, every victim-shard read eats the full delay
        # and the assertion trips — it cannot pass vacuously.
        "gray_delay_ms": 3000.0,
        "gray_p99_bound_ms": 2000.0 if smoke else 1000.0,
        # ISSUE 20 offload-fallback gate: client write p99 bound while
        # launch faults land on the csum/compressor services (same
        # generosity rationale — catches seconds-scale stalls, not noise)
        "offload_p99_bound_ms": 2000.0 if smoke else 1000.0,
    }
    return asyncio.run(_run(cfg))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="fast seed-fixed variant (tier-1)")
    ap.add_argument("--seed", type=int, default=0xC405)
    ap.add_argument("--osds", type=int, default=5)
    ap.add_argument("--objects", type=int, default=24)
    ap.add_argument("--pg-num", type=int, default=4)
    ap.add_argument("--out", default="",
                    help="also write the report JSON to this file (bench.py "
                         "folds chaos_p99_ms/recovery_occupancy from it via "
                         "BENCH_CHAOS_JSON)")
    args = ap.parse_args(argv)
    try:
        report = run_chaos(
            seed=args.seed, smoke=args.smoke, osds=args.osds,
            objects=args.objects, pg_num=args.pg_num,
        )
    except Exception as e:
        # EVERY failure's payload must reach --out (not just the
        # convergence errors): a stale success report from a previous
        # run would otherwise be folded into the NEXT bench line as if
        # this round had converged
        payload = json.dumps({"converged": False, "error": repr(e)})
        if args.out:
            with open(args.out, "w") as f:
                f.write(payload)
        print(payload)
        return 1
    payload = json.dumps(report, indent=2, sort_keys=True, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload)
    print(payload)
    return 0 if report.get("converged") else 1


if __name__ == "__main__":
    sys.exit(main())
