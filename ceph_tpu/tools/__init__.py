"""Operator tools: file codec CLI, benchmark harness, bench sweep."""
