"""Flight-record → Chrome trace-event JSON exporter (ISSUE 8).

Turns the launch flight recorder's ring (ops/flight_recorder.py; asok
``dump_flight``) into a Chrome trace-event file loadable in Perfetto /
``chrome://tracing``, so an overlap gap is something you LOOK at instead
of infer:

- one process row ("devices") with a lane (tid) per device width the
  launches spanned (plus "host fallback" and "device cache" lanes),
  carrying ``h2d`` / ``kernel`` / ``d2h`` slices per launch plus
  explicit ``idle`` slices for the gaps between consecutive launches on
  the lane — the idle slices ARE the optimization target of ROADMAP
  item 2 (overlap H2D with the previous kernel).  Since ISSUE 11 the
  slices anchor on completion-ordered timestamps (``complete_ts``):
  under pipelined dispatch the kernel-wait slice ends where the work
  actually finished, and the lane distance between a launch's ``h2d``
  and its wait IS the overlap won (``overlap`` flag +
  ``inflight_depth`` ride the slice args);
- one process row ("aggregator") with a lane per aggregator group,
  carrying a ``queue_wait`` slice (submit→dispatch: time the window
  held the work) followed by the launch slice, flags in ``args``;
- one process row ("sched class") with a lane per QoS class (client /
  recovery / background — the ISSUE 9 launch scheduler's lanes), same
  queue_wait + launch slices: a priority inversion is a background
  launch slice sitting in front of a client lane's queue_wait, visible
  at a glance;
- one counter track ("hbm" row, ISSUE 13): the mempool ledger's
  resident-bytes level at each launch's dispatch, so memory pressure
  renders on the same timeline as the launches that caused it.

Usage::

    # from a live daemon
    python -m ceph_tpu.tools.trace_export --asok /path/osd.0.asok -o t.json
    # from a saved dump_flight payload
    python -m ceph_tpu.tools.trace_export --dump flight.json -o trace.json

Library surface: ``export_chrome_trace(records)`` returns the trace
dict; tests validate its contract (``traceEvents`` complete-event keys,
monotonic non-overlapping same-lane slices, µs timestamps).
"""

from __future__ import annotations

import argparse
import json
import sys

# lanes below this duration still render (Perfetto drops dur=0); one
# microsecond is the trace format's resolution anyway
_MIN_DUR_US = 1

# idle gaps shorter than this are rendering noise, not scheduling
# signal: two back-to-back launches always have a few µs between the
# reap of one and the dispatch of the next
IDLE_MIN_US = 50


def _us(t: float) -> int:
    return int(round(t * 1e6))


def _complete(name: str, pid: str, tid: str, ts_us: int, dur_us: int,
              args: dict | None = None) -> dict:
    ev = {
        "name": name,
        "ph": "X",  # complete event: ts + dur, one object per slice
        "pid": pid,
        "tid": tid,
        "ts": ts_us,
        "dur": max(_MIN_DUR_US, dur_us),
    }
    if args:
        ev["args"] = args
    return ev


def _flags_args(rec: dict) -> dict:
    args = {
        "seq": rec["seq"],
        "kind": rec["kind"],
        "tickets": rec["tickets"],
        "stripes": rec["stripes"],
        "batch": rec["batch"],
        "bytes": rec["bytes"],
        "devices": rec["devices"],
        "reason": rec.get("reason", ""),
    }
    # pipeline witness (ISSUE 11): how deep the in-flight ring was when
    # this launch dispatched (absent on pre-pipeline records)
    if rec.get("inflight_depth"):
        args["inflight_depth"] = rec["inflight_depth"]
    # truthy flags render as one sorted CSV; "hedged" (ISSUE 17) marks
    # decode launches fed by a winning speculative sub-read — the gray
    # failure a straggler would have caused is visible per launch
    flags = [k for k, v in rec.get("flags", {}).items() if v]
    if flags:
        args["flags"] = ",".join(sorted(flags))
    return args


def _completion_ts(rec: dict) -> float:
    """Completion-ordered sort/anchor timestamp (ISSUE 11): under
    pipelined dispatch the wall clock around the now-nonblocking calls
    no longer brackets the kernel, so device-lane slices order and
    anchor on when the WORK finished — ``complete_ts`` when the settle
    recorded one, else the legacy dispatch anchor."""
    return (
        rec.get("complete_ts")
        or rec.get("dispatch_ts")
        or rec.get("submit_ts", 0.0)
    )


def export_chrome_trace(records: list[dict], clog: list[dict] | None = None) -> dict:
    """Chrome trace dict from flight records (oldest first — re-sorted
    defensively).  Span-less records (raw dispatch-witness entries)
    render as instant-like 1 µs slices so the timeline still shows
    them.  ``clog`` (ISSUE 16) takes committed cluster-log entries
    (the `log last` shape) and renders each as a Perfetto instant
    event ("i") on a "cluster events" process row, one lane per
    channel — storm engage/shed, health transitions, and audit
    commands line up against the device work they explain."""
    events: list[dict] = []
    total_records = len(records)
    # recovery-storm wave records (ISSUE 15) get their own process row
    # below — they are admission spans, not device work, and would
    # fabricate device busy time if interleaved on the device lanes
    storm_recs = [r for r in records if r.get("kind") == "recovery_wave"]
    records = [r for r in records if r.get("kind") != "recovery_wave"]
    # device lanes: sequential per lane, with explicit idle gaps.  Lanes
    # split by device width: a 1-device launch and an 8-device launch
    # occupy different hardware, interleaving them on one lane would
    # fabricate overlap conflicts.
    by_lane: dict[str, list[dict]] = {}
    for rec in sorted(records, key=_completion_ts):
        if rec["flags"].get("cache_hit"):
            lane = "device cache"
        elif rec["flags"].get("fallback"):
            lane = "host fallback"
        else:
            lane = f"device x{rec['devices']}"
        by_lane.setdefault(lane, []).append(rec)
    for lane, recs in sorted(by_lane.items()):
        prev_end_us: int | None = None
        for rec in recs:
            start = rec["dispatch_ts"] or rec["submit_ts"]
            start_us = _us(start)
            if prev_end_us is not None:
                start_us = max(start_us, prev_end_us)  # never overlap a lane
                gap = start_us - prev_end_us
                if gap >= IDLE_MIN_US:
                    events.append(_complete(
                        "idle", "devices", lane, prev_end_us, gap,
                        {"gap_us": gap},
                    ))
            cursor = start_us
            # completion-ordered anchors (ISSUE 11): h2d sits at the
            # dispatch, the kernel-wait slice ENDS at complete_ts, d2h
            # follows it — the gap between h2d and the wait is time the
            # device worked under LATER launches' dispatches (overlap),
            # rendered as lane distance instead of a fabricated
            # contiguous busy block.  Records without complete_ts (old
            # dumps, raw records) keep the legacy contiguous layout.
            complete_us = _us(rec.get("complete_ts") or 0.0)
            spans = [
                ("h2d", rec.get("h2d_s", 0.0), None),
                (
                    "kernel",
                    rec.get("kernel_s", 0.0),
                    (complete_us - _us(rec.get("kernel_s", 0.0)))
                    if complete_us > 0
                    else None,
                ),
                (
                    "d2h",
                    rec.get("d2h_s", 0.0),
                    complete_us if complete_us > 0 else None,
                ),
            ]
            if not any(d > 0 for _n, d, _a in spans):
                # span-less raw record: one marker slice
                events.append(_complete(
                    f"{rec['kind']} launch", "devices", lane, cursor,
                    _MIN_DUR_US, _flags_args(rec),
                ))
                cursor += _MIN_DUR_US
            else:
                for name, dur, anchor in spans:
                    dur_us = _us(dur)
                    if dur_us <= 0:
                        continue
                    if anchor is not None:
                        cursor = max(cursor, anchor)
                    events.append(_complete(
                        f"{rec['kind']}:{name}", "devices", lane, cursor,
                        dur_us, _flags_args(rec),
                    ))
                    cursor += max(_MIN_DUR_US, dur_us)
            prev_end_us = cursor
    # aggregator-group lanes: queue_wait then the whole launch span, per
    # group — shows which window held work and for how long.  The same
    # rendering repeats on the "sched class" row with one lane per QoS
    # class (ISSUE 9), so client / recovery / background contention is
    # directly comparable: a background launch slice overlapping a
    # client lane's queue_wait IS the priority inversion.
    def _sequential_lanes(pid: str, lane_of) -> None:
        by_lane_: dict[str, list[dict]] = {}
        for rec in records:
            lane = lane_of(rec)
            if lane is not None:
                by_lane_.setdefault(lane, []).append(rec)
        for lane, recs in sorted(by_lane_.items()):
            prev_end = None
            for rec in sorted(recs, key=lambda r: r.get("submit_ts", 0.0)):
                start_us = _us(rec["submit_ts"])
                if prev_end is not None:
                    start_us = max(start_us, prev_end)
                cursor = start_us
                wait_us = _us(rec.get("queue_wait_s", 0.0))
                if wait_us > 0:
                    events.append(_complete(
                        "queue_wait", pid, lane, cursor, wait_us,
                        {"seq": rec["seq"]},
                    ))
                    cursor += max(_MIN_DUR_US, wait_us)
                settle = rec.get("settle_ts") or rec.get("dispatch_ts") or 0.0
                launch_us = max(
                    _MIN_DUR_US,
                    _us(settle)
                    - _us(rec.get("dispatch_ts") or rec["submit_ts"]),
                )
                events.append(_complete(
                    f"{rec['kind']} launch", pid, lane, cursor,
                    launch_us, _flags_args(rec),
                ))
                cursor += launch_us
                prev_end = cursor

    _sequential_lanes("aggregator", lambda rec: rec.get("group") or "#raw")
    # records that never passed through the launch scheduler (raw bench
    # loops, bulk eager calls) have no class and stay off this row
    _sequential_lanes("sched class", lambda rec: rec.get("sched_class") or None)
    # HBM counter track (ISSUE 13): the mempool ledger's resident-bytes
    # level at each launch's dispatch, as Chrome counter events ("C") —
    # memory pressure renders on the SAME timeline as the launches, so
    # a residency ramp lines up visually with the launches that caused
    # it.  Records from pre-ledger dumps (no hbm_bytes key) emit
    # nothing; an explicit 0 still plots (the drain back to baseline is
    # part of the signal).
    # recovery-storm row (ISSUE 15): one lane per storm group
    # ("storm:osd.N"), one slice per admitted wave — the decode
    # launches the wave co-rides show up on the device/sched rows at
    # the same timestamps, so batching (few wide launches under one
    # wave slice) is visible as lane alignment.
    storm_lanes: dict[str, list[dict]] = {}
    for rec in storm_recs:
        storm_lanes.setdefault(rec.get("group") or "storm", []).append(rec)
    for lane, recs in sorted(storm_lanes.items()):
        prev_end = None
        for rec in sorted(recs, key=lambda r: r.get("submit_ts", 0.0)):
            start_us = _us(rec["submit_ts"])
            if prev_end is not None:
                start_us = max(start_us, prev_end)
            dur_us = max(
                _MIN_DUR_US,
                _us(rec.get("settle_ts") or 0.0) - _us(rec["submit_ts"]),
            )
            events.append(_complete(
                f"wave ({rec.get('stripes', 0)} objs, "
                f"{rec.get('tickets', 0)} pgs)",
                "recovery storm", lane, start_us, dur_us,
                {"seq": rec["seq"], "objects": rec.get("stripes", 0),
                 "pgs": rec.get("tickets", 0)},
            ))
            prev_end = start_us + dur_us
    for rec in sorted(records, key=_completion_ts):
        if "hbm_bytes" not in rec:
            continue
        events.append({
            "name": "hbm_resident_bytes",
            "ph": "C",
            "pid": "hbm",
            "tid": "hbm",
            "ts": _us(rec.get("dispatch_ts") or rec.get("submit_ts", 0.0)),
            "args": {"bytes": int(rec["hbm_bytes"])},
        })
    # cluster-events row (ISSUE 16): clog entries as instant events,
    # one lane per channel.  Entries carry wall-clock stamps while the
    # flight recorder is monotonic-clocked, so the row is internally
    # ordered but only loosely aligned to the device rows — the SEQUENCE
    # (down → storm engage → waves → complete) is the signal.
    for e in sorted(clog or [], key=lambda e: e.get("stamp", 0.0)):
        ev = {
            "name": str(e.get("msg", ""))[:120] or "(empty)",
            "ph": "i",
            "s": "t",  # thread-scoped instant: a tick on its lane
            "pid": "cluster events",
            "tid": str(e.get("channel", "cluster")),
            "ts": _us(float(e.get("stamp", 0.0))),
            "args": {
                "who": e.get("who", "?"),
                "severity": e.get("prio", "info"),
                **({"code": e["code"]} if e.get("code") else {}),
            },
        }
        events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "ceph_tpu flight recorder",
            "records": total_records,
        },
    }


def validate_chrome_trace(trace: dict) -> None:
    """The contract tests pin (and Perfetto needs): every event is a
    complete event ("X") with name/ph/pid/tid/ts/dur and no two slices
    on one (pid, tid) lane overlapping, or a counter event ("C", the
    ISSUE 13 HBM track) with a numeric-valued args series — counters
    are levels, not slices, so they carry no dur and may share
    timestamps."""
    events = trace["traceEvents"]
    lanes: dict[tuple, int] = {}
    slices = []
    for ev in events:
        if ev.get("ph") == "i":
            # instant event (ISSUE 16 cluster-events track): a point in
            # time, no dur, timestamps may repeat on a lane
            for key in ("name", "pid", "tid", "ts"):
                assert key in ev, f"instant event missing {key}: {ev}"
            assert ev.get("s") in ("t", "p", "g"), (
                f"instant event with bad scope: {ev}"
            )
            assert isinstance(ev["ts"], int) and ev["ts"] >= 0, ev
            continue
        if ev.get("ph") == "C":
            for key in ("name", "pid", "ts", "args"):
                assert key in ev, f"counter event missing {key}: {ev}"
            assert isinstance(ev["ts"], int) and ev["ts"] >= 0, ev
            assert ev["args"] and all(
                isinstance(v, (int, float)) for v in ev["args"].values()
            ), f"counter event with non-numeric series: {ev}"
            continue
        for key in ("name", "ph", "pid", "tid", "ts", "dur"):
            assert key in ev, f"event missing {key}: {ev}"
        assert ev["ph"] == "X", f"non-complete event {ev}"
        assert isinstance(ev["ts"], int) and ev["ts"] >= 0, ev
        assert isinstance(ev["dur"], int) and ev["dur"] >= 1, ev
        slices.append(ev)
    for ev in sorted(slices, key=lambda e: (e["pid"], e["tid"], e["ts"])):
        lane = (ev["pid"], ev["tid"])
        last_end = lanes.get(lane, -1)
        assert ev["ts"] >= last_end, (
            f"overlapping slices on lane {lane}: event at {ev['ts']} "
            f"starts before previous slice ended at {last_end}"
        )
        lanes[lane] = ev["ts"] + ev["dur"]


def _load_records(args) -> list[dict]:
    if args.asok:
        from ceph_tpu.common.admin_socket import admin_command

        return admin_command(args.asok, "dump_flight")["records"]
    if args.dump:
        with open(args.dump) as f:
            payload = json.load(f)
        return payload["records"] if isinstance(payload, dict) else payload
    # default: the in-process recorder (useful from a REPL/bench import)
    from ceph_tpu.ops.flight_recorder import flight_recorder

    return flight_recorder().records()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--asok", help="daemon admin socket to dump_flight from")
    src.add_argument("--dump", help="saved dump_flight JSON payload")
    ap.add_argument("--clog",
                    help="cluster-log JSON to merge as a 'cluster events' "
                         "instant-event track (a `log last` payload or a "
                         "bare entry list)")
    ap.add_argument("-o", "--out", default="-",
                    help="output trace file (default stdout)")
    args = ap.parse_args(argv)
    clog = None
    if args.clog:
        with open(args.clog) as f:
            payload = json.load(f)
        clog = payload["entries"] if isinstance(payload, dict) else payload
    trace = export_chrome_trace(_load_records(args), clog=clog)
    validate_chrome_trace(trace)
    payload = json.dumps(trace, indent=1)
    if args.out == "-":
        print(payload)
    else:
        with open(args.out, "w") as f:
            f.write(payload)
        print(
            f"wrote {len(trace['traceEvents'])} events to {args.out} "
            "(load in Perfetto / chrome://tracing)",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
