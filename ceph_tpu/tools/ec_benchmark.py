"""ceph_erasure_code_benchmark equivalent — the reference metric harness.

Mirror of /root/reference/src/test/erasure-code/ceph_erasure_code_benchmark.cc
(CLI :49-153, encode loop :165-194, decode loop with random / fixed /
exhaustive erasure generation and content verification :211-326).  Output
format is the reference's: "<elapsed seconds>\\t<iterations * size / 1024>"
(seconds TAB KiB).

  python -m ceph_tpu.tools.ec_benchmark -p tpu -P k=8 -P m=3 -S 1048576 -i 100
  python -m ceph_tpu.tools.ec_benchmark -w decode -e 2 --erasures-generation \\
      exhaustive -p tpu -P k=8 -P m=3 -S 1048576 -i 100

One deviation, documented: each encode iteration XORs a counter into the
first byte of the input so a caching runtime (the axon relay memoizes
identical launches) cannot elide repeated iterations; the reference's
fixed 'X'-fill buffer predates such runtimes.
"""

from __future__ import annotations

import argparse
import itertools
import random
import sys
import time

import numpy as np

from ceph_tpu.codec import registry as registry_mod
from ceph_tpu.codec.interface import EcError


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ec_benchmark", description=__doc__)
    p.add_argument("-p", "--plugin", default="tpu")
    p.add_argument(
        "-P",
        "--parameter",
        action="append",
        default=[],
        help="profile k=v pairs (repeatable)",
    )
    p.add_argument("-S", "--size", type=int, default=1 << 20)
    p.add_argument("-i", "--iterations", type=int, default=1)
    p.add_argument(
        "-w",
        "--workload",
        choices=("encode", "encode-pipelined", "decode", "repair"),
        default="encode",
    )
    p.add_argument("-e", "--erasures", type=int, default=1)
    p.add_argument("--depth", type=int, default=4,
                   help="encode-pipelined in-flight launch depth")
    p.add_argument(
        "--erased",
        action="append",
        type=int,
        default=None,
        help="fixed chunk ids to erase (repeatable)",
    )
    p.add_argument(
        "--erasures-generation",
        choices=("random", "exhaustive"),
        default="random",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def make_codec(args):
    profile = {}
    for token in args.parameter:
        key, val = token.split("=", 1)
        profile[key] = val
    return registry_mod.instance().factory(args.plugin, profile)


def run_encode(ec, args) -> float:
    n = ec.get_chunk_count()
    want = set(range(n))
    buf = np.random.default_rng(0).integers(0, 256, args.size, dtype=np.uint8)
    start = time.perf_counter()
    for i in range(args.iterations):
        buf[0] ^= np.uint8(i + 1)  # defeat identical-launch caching
        ec.encode(want, buf)
    return time.perf_counter() - start


def run_encode_pipelined(ec, args, depth: int | None = None) -> float:
    """Pipelined chunk encodes through the EncodePipeline completion
    queue: device launches overlap the host-side stripe preparation (the
    AIO-queue shape in front of ec_encode_data).  Stripes are generated
    INSIDE the timed loop — that host work is exactly what the pipeline
    overlaps, and pre-materializing every iteration would OOM large
    sweeps."""
    from ..codec.matrix_codec import EncodePipeline

    k, n = ec.get_data_chunk_count(), ec.get_chunk_count()
    chunk = ec.get_chunk_size(args.size)
    rng = np.random.default_rng(0)
    pipe = EncodePipeline(
        ec, depth=depth if depth is not None else getattr(args, "depth", 4)
    )
    start = time.perf_counter()
    for i in range(args.iterations):
        chunks = {
            ec.chunk_index(j): rng.integers(0, 256, chunk, dtype=np.uint8)
            if j < k
            else np.zeros(chunk, dtype=np.uint8)
            for j in range(n)
        }
        pipe.submit(chunks)
        pipe.poll()  # reap whatever already finished, without blocking
    pipe.flush()
    return time.perf_counter() - start


def run_decode(ec, args) -> float:
    n = ec.get_chunk_count()
    buf = np.random.default_rng(0).integers(0, 256, args.size, dtype=np.uint8)
    encoded = ec.encode(set(range(n)), buf)
    rng = random.Random(0)

    if args.erased:
        patterns = itertools.repeat(tuple(args.erased))
    elif args.erasures_generation == "exhaustive":
        patterns = itertools.cycle(
            itertools.combinations(range(n), args.erasures)
        )
    else:
        patterns = (
            tuple(rng.sample(range(n), args.erasures)) for _ in itertools.count()
        )

    elapsed = 0.0
    for _, erasures in zip(range(args.iterations), patterns):
        avail = {i: encoded[i] for i in range(n) if i not in erasures}
        t0 = time.perf_counter()
        decoded = ec.decode(set(erasures), avail)
        elapsed += time.perf_counter() - t0
        # content verification (reference decode_erasures :211-258)
        for e in erasures:
            if not np.array_equal(decoded[e], encoded[e]):
                raise SystemExit(f"decode mismatch for erasures {erasures}")
    return elapsed


def run_repair(ec, args) -> tuple[float, int, int]:
    """Single-chunk repair via minimum_to_decode's sub-chunk read plan.

    The regenerating-code metric (BASELINE config 4): a CLAY codec's plan
    reads d helpers x sub_chunk_no/q sub-chunks — d/(d-k+1) chunks' worth —
    where an MDS code reads k full chunks.  Returns (elapsed, bytes_read,
    bytes_repaired); the read plan mirrors ECBackend's fragmented sub-chunk
    reads (/root/reference/src/osd/ECBackend.cc:1047-1068; repair plan
    clay/ErasureCodeClay.cc:363-377).
    """
    n = ec.get_chunk_count()
    buf = np.random.default_rng(0).integers(0, 256, args.size, dtype=np.uint8)
    encoded = ec.encode(set(range(n)), buf)
    chunk_size = len(encoded[0])
    sub = chunk_size // ec.get_sub_chunk_count()
    rng = random.Random(0)

    elapsed, bytes_read, bytes_repaired = 0.0, 0, 0
    for i in range(args.iterations):
        lost = args.erased[0] if args.erased else rng.randrange(n)
        avail = set(range(n)) - {lost}
        minimum = ec.minimum_to_decode({lost}, avail)
        helpers: dict[int, np.ndarray] = {}
        for node, runs in minimum.items():
            frags = [
                encoded[node][off * sub : (off + count) * sub]
                for off, count in runs
            ]
            helpers[node] = np.concatenate(frags)
            bytes_read += len(helpers[node])
        t0 = time.perf_counter()
        decoded = ec.decode({lost}, helpers, chunk_size)
        elapsed += time.perf_counter() - t0
        if not np.array_equal(decoded[lost], encoded[lost]):
            raise SystemExit(f"repair mismatch for lost chunk {lost}")
        bytes_repaired += chunk_size
    return elapsed, bytes_read, bytes_repaired


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        ec = make_codec(args)
    except EcError as e:
        print(e, file=sys.stderr)
        return 1
    if args.workload == "encode":
        elapsed = run_encode(ec, args)
    elif args.workload == "encode-pipelined":
        elapsed = run_encode_pipelined(ec, args)
    elif args.workload == "decode":
        elapsed = run_decode(ec, args)
    else:
        elapsed, bytes_read, bytes_repaired = run_repair(ec, args)
        # repair emits an extra TAB field pair: read/repaired byte ratio —
        # the regenerating-code repair-bandwidth saving
        print(
            f"{elapsed:.6f}\t{args.iterations * args.size / 1024:.0f}"
            f"\t{bytes_read}\t{bytes_repaired}"
        )
        return 0
    print(f"{elapsed:.6f}\t{args.iterations * args.size / 1024:.0f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
