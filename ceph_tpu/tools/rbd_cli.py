"""rbd CLI — mirror of src/tools/rbd (image admin commands).

Targets a running cluster via the vstart cluster file:

    python -m ceph_tpu.tools.rbd_cli -p rbdpool create vol1 --size 4194304
    python -m ceph_tpu.tools.rbd_cli -p rbdpool snap create vol1@s1
    python -m ceph_tpu.tools.rbd_cli -p rbdpool clone vol1@s1 vol2
    python -m ceph_tpu.tools.rbd_cli -p rbdpool info vol2

Image@snap arguments use the reference's `image@snap` spelling.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..client import Rados
from ..client.rados import RadosError
from ..rbd import RBD, RbdError
from .vstart import CLUSTER_FILE, load_monmap


def _split_spec(spec: str) -> tuple[str, str]:
    image, _, snap = spec.partition("@")
    return image, snap


async def _run(args) -> int:
    client = Rados(load_monmap(args.cluster_file), name="client.rbd-cli")
    await client.connect()
    try:
        words = args.words
        op = words[0]

        def need(n: int, usage: str) -> None:
            if len(words) < n:
                raise RbdError(22, f"usage: {usage}")

        try:
            ioctx = await client.open_ioctx(args.pool)
            rbd = RBD(ioctx)
            if op in ("create", "resize") and args.size is None:
                # an implicit default here could silently SHRINK an image
                print(f"rbd: {op} requires an explicit --size", file=sys.stderr)
                return 1
            if op == "create":
                need(2, "create <image> --size N")
                await rbd.create(words[1], args.size, order=args.order)
                print(f"created {words[1]} ({args.size} bytes)")
            elif op in ("ls", "list"):
                for name in await rbd.list():
                    print(name)
            elif op in ("rm", "remove"):
                need(2, "rm <image>")
                await rbd.remove(words[1])
            elif op == "info":
                need(2, "info <image>")
                img = await rbd.open(words[1])
                info = {
                    "name": img.name,
                    "id": img.id,
                    "size": img.size,
                    "order": img.order,
                    "snapshots": await img.snap_list(),
                }
                if img.header.get("parent"):
                    p = img.header["parent"]
                    info["parent"] = f"{p['image_name']}@{p['snap_name']}"
                    info["overlap"] = p["overlap"]
                print(json.dumps(info, indent=2))
            elif op == "resize":
                need(2, "resize <image> --size N")
                img = await rbd.open(words[1])
                await img.resize(args.size)
            elif op == "export":
                need(3, "export <image[@snap]> <file>")
                image, snap = _split_spec(words[1])
                img = await rbd.open(image)
                data = await img.export(snap_name=snap or None)
                with open(words[2], "wb") as f:
                    f.write(data)
                print(f"exported {len(data)} bytes to {words[2]}")
            elif op == "import":
                need(3, "import <file> <image>")
                with open(words[1], "rb") as f:
                    data = f.read()
                await rbd.create(
                    words[2], len(data),
                    order=args.order,
                )
                img = await rbd.open(words[2])
                await img.import_bytes(data)
                print(f"imported {len(data)} bytes as {words[2]}")
            elif op == "cp":
                need(3, "cp <src[@snap]> <dst>")
                src_name, snap = _split_spec(words[1])
                src = await rbd.open(src_name)
                data = await src.export(snap_name=snap or None)
                await rbd.create(words[2], len(data), order=src.order)
                dst = await rbd.open(words[2])
                await dst.import_bytes(data)
            elif op == "clone":
                need(3, "clone <parent@snap> <child>")
                parent, snap = _split_spec(words[1])
                await rbd.clone(parent, snap, words[2])
                print(f"cloned {words[1]} -> {words[2]}")
            elif op == "flatten":
                need(2, "flatten <image>")
                img = await rbd.open(words[1])
                await img.flatten()
            elif op == "children":
                need(2, "children <parent@snap>")
                parent, snap = _split_spec(words[1])
                for child in await rbd.children(parent, snap):
                    print(child)
            elif op == "snap":
                need(3, "snap <create|rm|ls|rollback|protect|unprotect> <image[@snap]>")
                sub = words[1]
                image, snap = _split_spec(words[2])
                img = await rbd.open(image)
                if sub == "create":
                    await img.snap_create(snap)
                elif sub in ("rm", "remove"):
                    await img.snap_remove(snap)
                elif sub == "ls":
                    for name in await img.snap_list():
                        print(name)
                elif sub == "rollback":
                    await img.snap_rollback(snap)
                elif sub == "protect":
                    await img.snap_protect(snap)
                elif sub == "unprotect":
                    await img.snap_unprotect(snap)
                else:
                    print(f"unknown snap op {sub!r}", file=sys.stderr)
                    return 1
            elif op == "lock":
                need(3, "lock <ls|rm> <image> [entity cookie]")
                sub, image = words[1], words[2]
                img = await rbd.open(image)
                if sub == "ls":
                    for holder in await img.lock_owners():
                        print(json.dumps(holder))
                elif sub == "rm":
                    need(5, "lock rm <image> <entity> <cookie>")
                    await img.break_lock(words[3], words[4])
                else:
                    print(f"unknown lock op {sub!r}", file=sys.stderr)
                    return 1
            else:
                print(f"unknown op {op!r}", file=sys.stderr)
                return 1
        except RbdError as e:
            print(f"rbd: {e}", file=sys.stderr)
            return 1
        except RadosError as e:
            print(f"rbd: {e}", file=sys.stderr)
            return 1
        return 0
    finally:
        await client.shutdown()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-p", "--pool", required=True)
    p.add_argument("--cluster-file", default=CLUSTER_FILE)
    p.add_argument(
        "--size", type=int, default=None,
        help="bytes; REQUIRED for create/resize",
    )
    p.add_argument("--order", type=int, default=22)
    p.add_argument(
        "words", nargs="+",
        help="create|ls|rm|info|resize|clone|flatten|children|snap <op> "
        "<image[@snap]>|lock <op> <image>",
    )
    sys.exit(asyncio.run(_run(p.parse_args())))


if __name__ == "__main__":
    main()
