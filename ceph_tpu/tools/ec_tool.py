"""ceph-erasure-code-tool equivalent — file-level encode/decode CLI.

Mirror of /root/reference/src/tools/erasure-code/ceph-erasure-code-tool.cc,
whose command surface (and the byte-identity test harness built on it,
src/test/ceph-erasure-code-tool/test_ceph-erasure-code-tool.sh) is the model
for our parity checks:

  test-plugin-exists <plugin>
  validate-profile   <profile> [chunk_count|data_chunk_count|coding_chunk_count]
  calc-chunk-size    <profile> <object_size>
  encode             <profile> <stripe_unit> <want_to_encode> <file>
  decode             <profile> <stripe_unit> <want_to_read>   <file>

Profiles are comma-separated k=v lists (e.g. "plugin=tpu,technique=cauchy,
k=4,m=2").  encode reads <file> and writes <file>.<chunk> per requested
chunk; decode reads <file>.<chunk> fragments and writes <file>.decoded.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from ceph_tpu.codec import registry as registry_mod
from ceph_tpu.codec.interface import EcError, Profile


def parse_profile(text: str) -> tuple[str, Profile]:
    profile: Profile = {}
    for token in text.split(","):
        token = token.strip()
        if not token:
            continue
        if "=" not in token:
            raise SystemExit(f"invalid profile token {token!r} (need k=v)")
        key, val = token.split("=", 1)
        profile[key] = val
    plugin = profile.pop("plugin", "tpu")
    return plugin, profile


def make_codec(text: str):
    plugin, profile = parse_profile(text)
    return registry_mod.instance().factory(plugin, profile)


def cmd_test_plugin_exists(args) -> int:
    try:
        registry_mod.instance().load(args.plugin)
        return 0
    except EcError as e:
        print(e, file=sys.stderr)
        return 1


def cmd_validate_profile(args) -> int:
    try:
        ec = make_codec(args.profile)
    except EcError as e:
        print(e, file=sys.stderr)
        return 1
    if args.quantity:
        values = {
            "chunk_count": ec.get_chunk_count(),
            "data_chunk_count": ec.get_data_chunk_count(),
            "coding_chunk_count": ec.get_coding_chunk_count(),
        }
        if args.quantity not in values:
            print(f"unknown quantity {args.quantity}", file=sys.stderr)
            return 1
        print(values[args.quantity])
    return 0


def cmd_calc_chunk_size(args) -> int:
    ec = make_codec(args.profile)
    print(ec.get_chunk_size(args.object_size))
    return 0


def _parse_want(text: str) -> set[int]:
    return {int(x) for x in text.split(",") if x.strip() != ""}


def cmd_encode(args) -> int:
    ec = make_codec(args.profile)
    try:
        with open(args.file, "rb") as f:
            data = f.read()
    except OSError as e:
        print(e, file=sys.stderr)
        return 1
    # stripe_unit semantics: the reference aligns the object to
    # stripe_unit * k before encoding (tool stripe handling).
    k = ec.get_data_chunk_count()
    stripe_width = args.stripe_unit * k
    padded_len = -(-len(data) // stripe_width) * stripe_width
    padded = data + b"\0" * (padded_len - len(data))
    want = _parse_want(args.want) if args.want else set(range(ec.get_chunk_count()))
    chunks = ec.encode(want, padded)
    for i, chunk in sorted(chunks.items()):
        with open(f"{args.file}.{i}", "wb") as f:
            f.write(np.asarray(chunk, dtype=np.uint8).tobytes())
    return 0


def cmd_decode(args) -> int:
    ec = make_codec(args.profile)
    chunks = {}
    for i in range(ec.get_chunk_count()):
        path = f"{args.file}.{i}"
        if os.path.exists(path):
            with open(path, "rb") as f:
                chunks[i] = np.frombuffer(f.read(), dtype=np.uint8)
    want = _parse_want(args.want) if args.want else None
    try:
        if want is None:
            out = ec.decode_concat(chunks)
            with open(f"{args.file}.decoded", "wb") as f:
                f.write(out.tobytes())
        else:
            decoded = ec.decode(want, chunks)
            for i in sorted(want):
                with open(f"{args.file}.{i}.decoded", "wb") as f:
                    f.write(np.asarray(decoded[i]).tobytes())
    except EcError as e:
        print(e, file=sys.stderr)
        return 1
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ec_tool", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("test-plugin-exists")
    sp.add_argument("plugin")
    sp.set_defaults(func=cmd_test_plugin_exists)

    sp = sub.add_parser("validate-profile")
    sp.add_argument("profile")
    sp.add_argument("quantity", nargs="?")
    sp.set_defaults(func=cmd_validate_profile)

    sp = sub.add_parser("calc-chunk-size")
    sp.add_argument("profile")
    sp.add_argument("object_size", type=int)
    sp.set_defaults(func=cmd_calc_chunk_size)

    sp = sub.add_parser("encode")
    sp.add_argument("profile")
    sp.add_argument("stripe_unit", type=int)
    sp.add_argument("want")
    sp.add_argument("file")
    sp.set_defaults(func=cmd_encode)

    sp = sub.add_parser("decode")
    sp.add_argument("profile")
    sp.add_argument("stripe_unit", type=int)
    sp.add_argument("want")
    sp.add_argument("file")
    sp.set_defaults(func=cmd_decode)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
