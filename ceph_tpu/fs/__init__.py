"""POSIX-style filesystem over RADOS (src/mds + src/client)."""

from .fs import FileSystem, FsError

__all__ = ["FileSystem", "FsError"]
