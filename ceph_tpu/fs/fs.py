"""FileSystem — the CephFS data model over RADOS (src/mds + src/client,
SURVEY.md §2.7).

The reference splits CephFS into metadata (MDS daemons journaling dirs/
inodes into a metadata pool) and data (file contents striped into a data
pool by the client, using the inode-number-derived object names
`<ino>.<objno>`).  This module keeps that split as a library:

- **Metadata pool**: one object per directory, `dir.<ino>`, holding the
  dentry map name → inode record {ino, type, size, mtime, layout} —
  the shape of the reference's CDir/CDentry/CInode stored in dirfrag
  objects (mds/CDir.cc commit path).  The root is `dir.1` (MDS_INO_ROOT).
- **Data pool**: file content striped via the striper with the file's
  layout (client/Inode file_layout_t), objects named `<ino:x>.<objno>` —
  matching the reference's data-object naming
  (client/Client.cc file object naming via file_to_extents).
- An inode allocator object hands out inos (the MDS's inotable).

Single-MDS-equivalent consistency: operations are read-modify-write on
one directory object (the reference serializes through the MDS journal;
here the library is the sole metadata writer — multi-writer coordination
is future work and noted as such).
"""

from __future__ import annotations

import json
import time

from ..common.errs import EEXIST, EINVAL, ENOENT
from ..striper import StripedObject, StripePolicy

ROOT_INO = 1  # MDS_INO_ROOT
INOTABLE_OID = "mds_inotable"


class FsError(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(f"{msg} (errno {self.errno})")


class FileSystem:
    """libcephfs-style surface (src/libcephfs.cc API shape) over a
    metadata IoCtx + data IoCtx pair."""

    def __init__(self, meta_ioctx, data_ioctx, layout: StripePolicy | None = None):
        self.meta = meta_ioctx
        self.data = data_ioctx
        self.layout = layout or StripePolicy(
            stripe_unit=64 * 1024, stripe_count=2, object_size=1 << 20
        )

    # -- bootstrap -------------------------------------------------------------

    async def mkfs(self) -> None:
        """Create the root directory + inode table (ceph fs new /
        MDS mkfs)."""
        await self.meta.write_full(INOTABLE_OID, json.dumps({"next": 2}).encode())
        await self._store_dir(ROOT_INO, {})

    async def _alloc_ino(self) -> int:
        table = json.loads((await self.meta.read(INOTABLE_OID)).decode())
        ino = table["next"]
        table["next"] = ino + 1
        await self.meta.write_full(INOTABLE_OID, json.dumps(table).encode())
        return ino

    # -- directory objects -----------------------------------------------------

    async def _load_dir(self, ino: int) -> dict:
        try:
            raw = await self.meta.read(f"dir.{ino}")
        except Exception:
            raise FsError(ENOENT, f"directory inode {ino} not found")
        return json.loads(raw.decode() or "{}")

    async def _store_dir(self, ino: int, entries: dict) -> None:
        await self.meta.write_full(f"dir.{ino}", json.dumps(entries).encode())

    # -- path walking (Server::rdlock_path_xlock_dentry analog) ----------------

    @staticmethod
    def _split(path: str) -> list[str]:
        return [p for p in path.strip("/").split("/") if p]

    async def _walk(self, path: str) -> tuple[int, dict]:
        """Resolve a directory path -> (dir ino, entries)."""
        ino = ROOT_INO
        entries = await self._load_dir(ino)
        for name in self._split(path):
            ent = entries.get(name)
            if ent is None:
                raise FsError(ENOENT, f"no such directory: {name}")
            if ent["type"] != "dir":
                raise FsError(EINVAL, f"{name} is not a directory")
            ino = ent["ino"]
            entries = await self._load_dir(ino)
        return ino, entries

    async def _walk_parent(self, path: str) -> tuple[int, dict, str]:
        parts = self._split(path)
        if not parts:
            raise FsError(EINVAL, "path resolves to root")
        parent = "/".join(parts[:-1])
        ino, entries = await self._walk(parent)
        return ino, entries, parts[-1]

    # -- namespace ops ---------------------------------------------------------

    async def mkdir(self, path: str) -> None:
        dino, entries, name = await self._walk_parent(path)
        if name in entries:
            raise FsError(EEXIST, f"{path} exists")
        ino = await self._alloc_ino()
        await self._store_dir(ino, {})
        entries[name] = {"type": "dir", "ino": ino, "mtime": time.time()}
        await self._store_dir(dino, entries)

    async def listdir(self, path: str = "/") -> list[str]:
        _ino, entries = await self._walk(path)
        return sorted(entries)

    async def stat(self, path: str) -> dict:
        if not self._split(path):
            return {"type": "dir", "ino": ROOT_INO, "size": 0}
        _dino, entries, name = await self._walk_parent(path)
        ent = entries.get(name)
        if ent is None:
            raise FsError(ENOENT, path)
        return dict(ent)

    async def rename(self, src: str, dst: str) -> None:
        """Server::handle_client_rename (same-or-cross directory).
        POSIX replace semantics: an existing destination FILE is
        replaced (its data objects removed); renaming over a directory
        fails (the MDS requires an empty dir target; we reject outright)."""
        sparts, dparts = self._split(src), self._split(dst)
        if dparts[: len(sparts)] == sparts:
            # moving a directory into its own subtree would detach it into
            # an unreachable cycle (POSIX/MDS: EINVAL)
            raise FsError(EINVAL, f"cannot move {src} inside itself")
        sdino, sentries, sname = await self._walk_parent(src)
        if sname not in sentries:
            raise FsError(ENOENT, src)
        ddino, dentries, dname = await self._walk_parent(dst)
        if sdino == ddino:
            dentries = sentries
        existing = dentries.get(dname)
        if existing is not None:
            if existing["type"] == "dir":
                raise FsError(EINVAL, f"{dst} is a directory")
            await self._file_data(existing["ino"]).remove()
        ent = sentries.pop(sname)
        dentries[dname] = ent
        await self._store_dir(sdino, sentries)
        if sdino != ddino:
            await self._store_dir(ddino, dentries)

    async def rmdir(self, path: str) -> None:
        dino, entries, name = await self._walk_parent(path)
        ent = entries.get(name)
        if ent is None:
            raise FsError(ENOENT, path)
        if ent["type"] != "dir":
            raise FsError(EINVAL, f"{path} is not a directory")
        victim = await self._load_dir(ent["ino"])
        if victim:
            raise FsError(EINVAL, f"{path} not empty")
        try:
            await self.meta.remove(f"dir.{ent['ino']}")
        except Exception:
            pass
        del entries[name]
        await self._store_dir(dino, entries)

    # -- file ops --------------------------------------------------------------

    def _file_data(self, ino: int) -> StripedObject:
        # data objects "<ino hex>.<objno>" (Client file_to_extents naming)
        return StripedObject(self.data, f"{ino:x}", policy=self.layout)

    async def write_file(self, path: str, data: bytes, off: int = 0) -> None:
        """create-or-open + write (Client::ll_write path, collapsed)."""
        dino, entries, name = await self._walk_parent(path)
        ent = entries.get(name)
        if ent is None:
            ino = await self._alloc_ino()
            ent = {"type": "file", "ino": ino, "size": 0, "mtime": time.time()}
        elif ent["type"] != "file":
            raise FsError(EINVAL, f"{path} is a directory")
        await self._file_data(ent["ino"]).write(data, off)
        ent["size"] = max(ent["size"], off + len(data))
        ent["mtime"] = time.time()
        entries[name] = ent
        await self._store_dir(dino, entries)

    async def read_file(self, path: str, length: int = 0, off: int = 0) -> bytes:
        st = await self.stat(path)
        if st["type"] != "file":
            raise FsError(EINVAL, f"{path} is a directory")
        return await self._file_data(st["ino"]).read(length, off)

    async def truncate_file(self, path: str, size: int) -> None:
        dino, entries, name = await self._walk_parent(path)
        ent = entries.get(name)
        if ent is None or ent["type"] != "file":
            raise FsError(ENOENT, path)
        await self._file_data(ent["ino"]).truncate(size)
        ent["size"] = size
        await self._store_dir(dino, entries)

    async def unlink(self, path: str) -> None:
        dino, entries, name = await self._walk_parent(path)
        ent = entries.get(name)
        if ent is None:
            raise FsError(ENOENT, path)
        if ent["type"] != "file":
            raise FsError(EINVAL, f"{path} is a directory; use rmdir")
        await self._file_data(ent["ino"]).remove()
        del entries[name]
        await self._store_dir(dino, entries)
