"""cephx challenge/response — mirror of src/auth/cephx/.

The reference's cephx protocol (CephxProtocol.h; docs in
doc/dev/cephx_protocol.rst) is a Kerberos-like scheme: the client proves
knowledge of its secret by encrypting a server challenge, then receives
session-keyed tickets.  This module keeps the protocol shape over the
msgr2 frame channel (frames_v2.h auth frame tags) with HMAC-SHA256 as
the proof primitive instead of AES encryption:

  client                               server
    AUTH_REQUEST [entity] ---------------->
    <------------- AUTH_MORE [server_challenge]
    AUTH_MORE [client_challenge, proof] -->      proof = HMAC(secret,
    <--- AUTH_DONE [confirm, ticket]             sc || cc)
         confirm = HMAC(secret, cc || sc)        (mutual: client verifies
                                                  confirm)

A failed lookup or bad proof gets AUTH_BAD and a closed connection —
the reference's -EACCES path (CephxServiceHandler::handle_request).
Tickets are HMAC-signed {entity, expiry} blobs under the service key
(CephxSessionHandler's service secret), honored on fast reconnects.
"""

from __future__ import annotations

import hmac
import json
import hashlib
import secrets as _secrets
import time

from ..common.log import dout
from .keyring import KeyRing, generate_secret

# Auth frame tags (frames_v2.h Tag::AUTH_*)
TAG_AUTH_REQUEST = 10
TAG_AUTH_MORE = 11
TAG_AUTH_DONE = 12
TAG_AUTH_BAD = 13

CHALLENGE_LEN = 16
TICKET_VALIDITY = 3600.0  # auth_service_ticket_ttl
PROOF_FRESHNESS = 60.0  # seconds a ticket proof's timestamp stays valid


class AuthError(Exception):
    pass


def _hmac(secret: bytes, *parts: bytes) -> bytes:
    return hmac.new(secret, b"".join(parts), hashlib.sha256).digest()


class CephxAuth:
    """Both ends of the handshake; attach one to a Messenger.

    The server side needs the full keyring (mons/daemons verifying
    peers); the client side needs its own (entity, secret).
    """

    def __init__(
        self,
        entity: str,
        secret: bytes,
        keyring: KeyRing | None = None,
        service_secret: bytes | None = None,
    ):
        self.entity = entity
        self.secret = secret
        self.keyring = keyring
        self.service_secret = service_secret or generate_secret()
        # peer addr -> ticket from that peer's service (CephxTicketManager)
        self._tickets: dict[str, bytes] = {}
        # recently accepted ticket proofs (replay rejection window)
        self._seen_proofs: dict[bytes, float] = {}

    @classmethod
    def for_daemon(cls, entity: str, keyring: KeyRing) -> "CephxAuth":
        secret = keyring.get(entity)
        if secret is None:
            raise AuthError(f"no key for {entity} in keyring")
        return cls(entity, secret, keyring=keyring)

    @classmethod
    def for_client(cls, entity: str, secret: bytes) -> "CephxAuth":
        return cls(entity, secret)

    # -- client side (CephxClientHandler) --------------------------------------

    async def client_auth(
        self, send_frame, recv_frame, peer: str = ""
    ) -> tuple[bytes, bytes]:
        """Run the client handshake over frame callables; returns
        (session ticket, connection secret).  Raises AuthError on
        rejection.

        The connection secret is derived from the handshake transcript
        (the reference's CephxConnectionHandler connection_secret) and
        keys msgr2 secure mode.

        A ticket previously issued by `peer` rides in the request; if the
        server accepts it the challenge round-trip is skipped (the
        reference's ticket-based fast path, CephxTicketManager)."""
        from ..msg.crypto import derive_session_key

        cached = self._tickets.get(peer, b"")
        if cached:
            # Ticket + proof-of-secret: possession of a (plaintext-carried)
            # ticket alone must not authenticate — the proof binds it to
            # the entity key and a fresh timestamp (the reference's
            # CEPHX_V2 authorizer carries the same freshness binding).
            ts = str(time.time()).encode()
            req = [self.entity.encode(), cached, ts, _hmac(self.secret, cached, ts)]
        else:
            req = [self.entity.encode()]
        await send_frame(TAG_AUTH_REQUEST, req)
        tag, segs = await recv_frame()
        if tag == TAG_AUTH_DONE and cached:
            # Ticket accepted: server proves key knowledge over the ticket.
            confirm, ticket = segs[0], segs[1]
            if not hmac.compare_digest(confirm, _hmac(self.secret, cached)):
                raise AuthError("server failed mutual auth on ticket path")
            self._tickets[peer] = ticket
            return ticket, derive_session_key(self.secret, cached, ts)
        if tag != TAG_AUTH_MORE:
            raise AuthError(f"server rejected auth request (tag {tag})")
        server_challenge = segs[0]
        client_challenge = _secrets.token_bytes(CHALLENGE_LEN)
        proof = _hmac(self.secret, server_challenge, client_challenge)
        await send_frame(TAG_AUTH_MORE, [client_challenge, proof])
        tag, segs = await recv_frame()
        if tag != TAG_AUTH_DONE:
            raise AuthError("bad credentials (server sent AUTH_BAD)")
        confirm, ticket = segs[0], segs[1]
        expect = _hmac(self.secret, client_challenge, server_challenge)
        if not hmac.compare_digest(confirm, expect):
            raise AuthError("server failed mutual auth (wrong service key?)")
        if peer:
            self._tickets[peer] = ticket
        return ticket, derive_session_key(
            self.secret, server_challenge, client_challenge
        )

    # -- server side (CephxServiceHandler) -------------------------------------

    async def server_auth(self, send_frame, recv_frame) -> tuple[str, bytes]:
        """Run the server handshake; returns (authenticated entity name,
        connection secret).  Raises AuthError (after sending AUTH_BAD) on
        failure."""
        from ..msg.crypto import derive_session_key

        tag, segs = await recv_frame()
        if tag != TAG_AUTH_REQUEST:
            await send_frame(TAG_AUTH_BAD, [b"expected auth request"])
            raise AuthError("protocol error: no auth request")
        entity = segs[0].decode()
        secret = self.keyring.get(entity) if self.keyring else None
        if len(segs) >= 4 and secret is not None:
            # Ticket fast path: the ticket must verify AND the client must
            # prove key knowledge over (ticket, fresh timestamp); replayed
            # proofs are rejected (the reference's CEPHX_V2 nonce window).
            presented, ts, proof = segs[1], segs[2], segs[3]
            if (
                self.verify_ticket(presented) == entity
                and self._fresh(ts)
                and hmac.compare_digest(proof, _hmac(secret, presented, ts))
                and self._unseen(proof)
            ):
                confirm = _hmac(secret, presented)
                renewed = self.issue_ticket(entity)
                await send_frame(TAG_AUTH_DONE, [confirm, renewed])
                return entity, derive_session_key(secret, presented, ts)
        server_challenge = _secrets.token_bytes(CHALLENGE_LEN)
        if secret is None:
            # Don't leak which entities exist: issue a challenge anyway and
            # fail the proof (the reference logs and rejects).
            secret = _secrets.token_bytes(16)
            dout("auth", 5, f"cephx: unknown entity {entity}")
        await send_frame(TAG_AUTH_MORE, [server_challenge])
        tag, segs = await recv_frame()
        if tag != TAG_AUTH_MORE:
            await send_frame(TAG_AUTH_BAD, [b"expected proof"])
            raise AuthError("protocol error: no proof")
        client_challenge, proof = segs[0], segs[1]
        expect = _hmac(secret, server_challenge, client_challenge)
        if not hmac.compare_digest(proof, expect):
            await send_frame(TAG_AUTH_BAD, [b"bad proof"])
            raise AuthError(f"bad proof from {entity}")
        confirm = _hmac(secret, client_challenge, server_challenge)
        ticket = self.issue_ticket(entity)
        await send_frame(TAG_AUTH_DONE, [confirm, ticket])
        return entity, derive_session_key(
            secret, server_challenge, client_challenge
        )

    # -- ticket proof helpers --------------------------------------------------

    def _fresh(self, ts: bytes) -> bool:
        try:
            return abs(time.time() - float(ts.decode())) < PROOF_FRESHNESS
        except ValueError:
            return False

    def _unseen(self, proof: bytes) -> bool:
        """Reject replayed proofs inside the freshness window."""
        seen = self._seen_proofs
        now = time.time()
        for p, exp in list(seen.items()):
            if exp < now:
                del seen[p]
        if proof in seen:
            return False
        seen[proof] = now + PROOF_FRESHNESS
        return True

    # -- tickets (CephxSessionHandler) -----------------------------------------

    def issue_ticket(self, entity: str) -> bytes:
        body = json.dumps(
            {"entity": entity, "expires": time.time() + TICKET_VALIDITY}
        ).encode()
        sig = _hmac(self.service_secret, body)
        return len(body).to_bytes(4, "little") + body + sig

    def verify_ticket(self, ticket: bytes) -> str | None:
        """Entity name if the ticket is valid and unexpired, else None."""
        try:
            n = int.from_bytes(ticket[:4], "little")
            body, sig = ticket[4 : 4 + n], ticket[4 + n :]
            if not hmac.compare_digest(sig, _hmac(self.service_secret, body)):
                return None
            info = json.loads(body.decode())
            if info["expires"] < time.time():
                return None
            return info["entity"]
        except (ValueError, KeyError, json.JSONDecodeError):
            return None
