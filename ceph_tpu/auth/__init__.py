"""Auth subsystem — cephx-style shared-secret authentication
(SURVEY.md §1 row 3; src/auth/)."""

from .keyring import KeyRing, generate_secret
from .cephx import AuthError, CephxAuth

__all__ = ["KeyRing", "generate_secret", "CephxAuth", "AuthError"]
