"""KeyRing — mirror of src/auth/KeyRing.{h,cc}.

The reference stores per-entity base64 secrets in INI-style keyring
files (`[client.admin]\\n key = <base64>`); mons hold the authoritative
copy (AuthMonitor), daemons load theirs at boot.  Same format here.
"""

from __future__ import annotations

import base64
import os
import secrets as _secrets


def generate_secret() -> bytes:
    """A fresh 16-byte secret (CryptoKey::create AES-128 key size)."""
    return _secrets.token_bytes(16)


class KeyRing:
    """entity name -> secret bytes."""

    def __init__(self) -> None:
        self._keys: dict[str, bytes] = {}

    def add(self, entity: str, secret: bytes | None = None) -> bytes:
        secret = secret if secret is not None else generate_secret()
        self._keys[entity] = secret
        return secret

    def remove(self, entity: str) -> None:
        self._keys.pop(entity, None)

    def get(self, entity: str) -> bytes | None:
        return self._keys.get(entity)

    def entities(self) -> list[str]:
        return sorted(self._keys)

    def __contains__(self, entity: str) -> bool:
        return entity in self._keys

    def __len__(self) -> int:
        return len(self._keys)

    # -- keyring file format (KeyRing::encode_plaintext) ----------------------

    def dumps(self) -> str:
        out = []
        for entity in self.entities():
            key = base64.b64encode(self._keys[entity]).decode()
            out.append(f"[{entity}]\n\tkey = {key}\n")
        return "".join(out)

    @classmethod
    def loads(cls, text: str) -> "KeyRing":
        kr = cls()
        entity = None
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("[") and line.endswith("]"):
                entity = line[1:-1].strip()
            elif "=" in line and entity is not None:
                field, _, value = line.partition("=")
                if field.strip() == "key":
                    kr._keys[entity] = base64.b64decode(value.strip())
        return kr

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.dumps())
        os.chmod(path, 0o600)

    @classmethod
    def load(cls, path: str) -> "KeyRing":
        with open(path) as f:
            return cls.loads(f.read())
