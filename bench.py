"""Headline benchmark: RS(8,3) encode throughput, GB/s per chip.

The TPU analog of the reference harness invocation
`ceph_erasure_code_benchmark -p isa -P k=8 -P m=3 -S 1048576 -i 1000`
(/root/reference/src/erasure-code/isa/README:36-47; harness at
src/test/erasure-code/ceph_erasure_code_benchmark.cc): each "object" is a
1 MiB stripe split into eight 128 KiB data chunks; throughput counts input
object bytes per second of encode, exactly like the harness's
`iterations * size / elapsed`.  Stripes are batched and resident in HBM —
the codec's deep-batching design (SURVEY.md §7 step 3) that replaces the
reference's per-stripe CPU loop (src/osd/ECUtil.cc:139).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
vs_baseline is the ratio against the 40 GB/s/chip north-star target
(BASELINE.json).
"""

import json
import sys
import time

import numpy as np

NORTH_STAR_GBPS = 40.0


def main() -> None:
    import functools

    import jax
    import jax.numpy as jnp

    from ceph_tpu.gf import expand_matrix, isa_rs_vandermonde_matrix
    from ceph_tpu.ops.pallas_gf import CodingPlan
    from ceph_tpu.ops.xor_mm import xor_matmul

    k, m = 8, 3
    chunk = 128 * 1024  # 1 MiB object / 8 data chunks
    platform = jax.devices()[0].platform
    batch = 64 if platform != "cpu" else 2  # 64 MiB of object data per launch
    iters = 40 if platform != "cpu" else 3

    gfm = isa_rs_vandermonde_matrix(k, m)[k:]
    if platform == "tpu":
        plan = CodingPlan(gfm)
        encode_fn = plan
    else:
        bit_matrix = jnp.asarray(expand_matrix(gfm), dtype=jnp.uint8)
        encode_fn = functools.partial(xor_matmul, bit_matrix)

    rng = np.random.default_rng(0)
    data = jnp.asarray(
        rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8), dtype=jnp.uint8
    )

    # Serial-chain methodology: each launch's input depends on the previous
    # launch's parity (a 128-byte patch, updated in place via donation), so
    # runtime-level caching/elision of repeated identical launches cannot
    # inflate the number; the measured loop is real back-to-back encodes.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(d, p):
        patch = (p[:1, :1, :128] ^ jnp.uint8(1)).reshape(1, 1, 128)
        d2 = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
        return d2, encode_fn(d2)

    p = encode_fn(data)
    data, p = step(data, p)  # compile + warm
    jax.block_until_ready((data, p))

    t0 = time.perf_counter()
    for _ in range(iters):
        data, p = step(data, p)
    jax.block_until_ready((data, p))
    elapsed = time.perf_counter() - t0

    total_bytes = batch * k * chunk * iters  # input object bytes, harness semantics
    gbps = total_bytes / elapsed / 1e9
    print(
        f"[bench] platform={platform} batch={batch} iters={iters} "
        f"elapsed={elapsed:.4f}s",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "rs_8_3_encode_GBps_per_chip",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / NORTH_STAR_GBPS, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
