"""Headline benchmark: RS(8,3) encode throughput, GB/s per chip.

The TPU analog of the reference harness invocation
`ceph_erasure_code_benchmark -p isa -P k=8 -P m=3 -S 1048576 -i 1000`
(/root/reference/src/erasure-code/isa/README:36-47; harness at
src/test/erasure-code/ceph_erasure_code_benchmark.cc): each "object" is a
1 MiB stripe split into eight 128 KiB data chunks; throughput counts input
object bytes per second of encode, exactly like the harness's
`iterations * size / elapsed`.  Stripes are batched and resident in HBM —
the codec's deep-batching design (SURVEY.md §7 step 3) that replaces the
reference's per-stripe CPU loop (src/osd/ECUtil.cc:139).

The measured function is the SHIPPING path: the registered `tpu` plugin's
`encode_array` (the same cached-coder dispatch `encode_chunks` uses), which
on a TPU backend runs the fused Pallas kernel (ceph_tpu/ops/pallas_gf.py).
Before timing, the child asserts the kernel's parity bytes equal the host
GF oracle's on-chip — bytes first, then speed.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}
vs_baseline is the ratio against the 40 GB/s/chip north-star target
(BASELINE.json).

Robustness: the environment's TPU backend (axon) is known to sometimes fail
or hang during init.  The parent process therefore never imports jax; the
measurement runs in a child subprocess under a bounded deadline, attempted
on TPU first (with one retry for fast failures) and falling back to a CPU
child.  A TPU failure is recorded in the JSON as a structured `tpu_failure`
object — `{"cause": import_hang | backend_init_hang | stage_hang |
device_error, "stage": ..., "rc": ..., "detail": ...}` — and the CPU
number still satisfies the one-JSON-line contract.  The line is always
parseable; only if BOTH children fail is value 0, with the causes in an
`error` field.
"""

import json
import os
import subprocess
import sys
import time

NORTH_STAR_GBPS = 40.0

# Bounded deadlines so an axon backend-init hang cannot eat the whole round.
# Deadline covers backend init + one remote compile per tuned batch depth
# (first compiles are ~20-40 s each through the remote-compile helper).
# Kept under the 300 s wrapper the verify recipe uses around bench.py.
TPU_DEADLINE_S = float(os.environ.get("BENCH_TPU_TIMEOUT", "240"))
CPU_DEADLINE_S = float(os.environ.get("BENCH_CPU_TIMEOUT", "300"))
TPU_RETRIES = int(os.environ.get("BENCH_TPU_RETRIES", "2"))
# Staged child warm-up: each early stage (jax import, backend init, tiny
# compile probe) gets its own watchdog allowance, so a wedged backend
# fails in tens of seconds (rc=5, attributable stage in stderr) instead
# of silently eating the whole child deadline.
STAGE_TIMEOUT_S = float(os.environ.get("BENCH_STAGE_TIMEOUT", "60"))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT", "90"))
# Backend init (jax.devices()) gets its own SHORT allowance and a distinct
# exit code: rounds 4-5 lost whole rounds to the axon runtime wedging right
# here, so a hang costs ~45 s, the parent retries init-hangs exactly once
# (a transient tunnel blip recovers; a wedged one fails fast again), and
# then falls back to CPU with the round's deadline mostly intact.
BACKEND_INIT_TIMEOUT_S = float(os.environ.get("BENCH_BACKEND_INIT_TIMEOUT", "45"))


def _log(msg: str) -> None:
    print(f"[bench] {time.strftime('%H:%M:%S')} {msg}", file=sys.stderr, flush=True)


class _StageWatchdog:
    """Child-side watchdog over the warm-up stages.  A stage that
    overruns its allowance hard-exits the child with its stage's exit
    code: rc=5 for a generic stage hang (the parent treats that like a
    deadline: a hang will hang again, don't retry), rc=6 for a backend
    init hang specifically (the parent retries that exactly once)."""

    def __init__(self, clog):
        import threading

        self._clog = clog
        self._stage = None
        self._deadline = None
        self._rc = 5
        self._lock = threading.Lock()
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def stage(self, name: str, timeout_s: float, rc: int = 5) -> None:
        with self._lock:
            self._stage = name
            self._deadline = time.monotonic() + timeout_s
            self._rc = rc
        self._clog(f"stage: {name} (allowance {timeout_s:.0f}s)")

    def disarm(self) -> None:
        with self._lock:
            self._stage = None
            self._deadline = None

    def _run(self) -> None:
        while True:
            time.sleep(1.0)
            with self._lock:
                stage, deadline, rc = self._stage, self._deadline, self._rc
            if deadline is not None and time.monotonic() > deadline:
                self._clog(f"WATCHDOG: stage '{stage}' overran its allowance")
                # machine-readable failure stage on stdout: the parent
                # folds it into the JSON taxonomy (import_hang /
                # backend_init_hang / stage_hang) instead of a free-text
                # error string
                print(json.dumps({"failure_stage": stage}), flush=True)
                sys.stderr.flush()
                os._exit(rc)


def run_child(platform: str, mc_only: bool = False) -> None:
    """Child mode: do the actual measurement on the given platform.

    Progress is logged to stderr line-by-line so that a hang in backend init
    or compilation is attributable from the parent's captured output.

    `mc_only`: run ONLY the multichip stage (the parent spawns this as a
    separate CPU child with a forced 8-virtual-device mesh, so the
    per-chip headline never pays the virtual-device threadpool split).
    """

    def clog(msg: str) -> None:
        tag = f"{platform}-mc" if mc_only else platform
        print(f"[bench-child:{tag}] {msg}", file=sys.stderr, flush=True)

    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"

    watchdog = _StageWatchdog(clog)
    watchdog.stage("import_jax", STAGE_TIMEOUT_S)
    clog("importing jax")
    import functools

    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    watchdog.stage("backend_init", BACKEND_INIT_TIMEOUT_S, rc=6)
    clog("initializing backend (jax.devices())")
    dev = jax.devices()[0]
    got = dev.platform
    clog(f"backend up: {len(jax.devices())} x {got} ({dev.device_kind})")
    if platform == "tpu" and got == "cpu":
        clog("wanted TPU but only CPU available")
        sys.exit(3)

    from ceph_tpu.codec.registry import instance
    from ceph_tpu.gf import gf_matmul, isa_rs_vandermonde_matrix

    k, m = 8, 3
    chunk = 128 * 1024  # 1 MiB object / 8 data chunks
    on_tpu = got == "tpu"
    # Deep batching is the codec's design point: launch overhead through
    # the axon tunnel is ~2-3 ms regardless of size, so 64 MiB launches
    # cap at ~21 GB/s while 256 MiB launches run at the kernel's ~53 GB/s
    # bandwidth-bound rate.  256 MiB is the measured sweet spot AND the
    # safe ceiling: 512 MiB chained launches are what wedged the tunnel in
    # round 4 (benchmarks/diag/ONCHIP_NOTES_r4.md), and a single candidate
    # saves one ~30 s remote compile inside the driver's child deadline.
    try:
        env_batch = int(os.environ.get("BENCH_TPU_BATCH", "256"))
    except ValueError:
        clog("ignoring malformed BENCH_TPU_BATCH")
        env_batch = 256
    if env_batch <= 0:
        env_batch = 256
    # CPU fallback: deep batching matters here too — at batch=2 the
    # serial chain is dominated by per-step dispatch/update overhead
    # (~0.15 GB/s); batch=8 amortizes it (~1.8 GB/s measured with the
    # packed-plane kernel) while keeping the child well inside deadline.
    batch_candidates = (env_batch,) if on_tpu else (8,)
    iters = 40 if on_tpu else 8

    # The SHIPPING path: the registered `tpu` plugin's device encode — the
    # same dispatch encode_chunks uses (on TPU backends the cached
    # _DeviceCoder runs the fused Pallas kernel; VERDICT r3 item 1).
    clog("building codec via plugin registry")
    ec = instance().factory("tpu", {"k": str(k), "m": str(m)})
    encode_fn = ec.encode_array
    rng = np.random.default_rng(0)
    gfm = isa_rs_vandermonde_matrix(k, m)[k:]
    parity_checked = False

    # Tiny-batch compile probe BEFORE the tuned batch: exercises the whole
    # backend/compile/dispatch chain on a seconds-scale shape, so a wedged
    # backend trips the probe watchdog instead of hanging inside the big
    # (minutes-scale on a cold remote-compile path) tuned compile.
    watchdog.stage("warmup_probe", PROBE_TIMEOUT_S)
    t_probe = time.perf_counter()
    # 64 KiB: the smallest shape that takes the bulk kernel path (packed
    # plane / Pallas), so the probe compiles the same kernel family the
    # tuned batch will
    probe_in = rng.integers(0, 256, (1, k, 8192), dtype=np.uint8)
    probe_par = np.asarray(encode_fn(probe_in))
    if not np.array_equal(probe_par[0], gf_matmul(gfm, probe_in[0])):
        clog("PROBE PARITY MISMATCH vs host oracle")
        sys.exit(4)
    probe_s = time.perf_counter() - t_probe
    clog(f"warm-up probe OK ({probe_s:.2f}s)")
    watchdog.disarm()

    # Serial-chain methodology: each launch's input depends on the previous
    # launch's parity (a 128-byte patch, updated in place via donation), so
    # runtime-level caching/elision of repeated identical launches cannot
    # inflate the number; the measured loop is real back-to-back encodes.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(d, p):
        patch = (p[:1, :1, :128] ^ jnp.uint8(1)).reshape(1, 1, 128)
        d2 = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
        return d2, encode_fn(d2)

    def run_chain(batch: int, n: int) -> float:
        """GB/s (input bytes) over n chained launches at this depth.  A
        tiny device->host readback closes the timing window honestly: on
        the axon backend, block_until_ready alone has been observed to
        return before queued launches finish; materializing bytes cannot.
        """
        nonlocal parity_checked
        host = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
        data = jnp.asarray(host, dtype=jnp.uint8)
        # zeros seed: step only reads 128 bytes of p for the patch, and
        # the warm call below regenerates real parity — seeding through
        # encode_fn would cost a second remote compile per depth
        p = jnp.zeros((batch, m, chunk), jnp.uint8)
        data, p = step(data, p)  # compile + warm
        jax.block_until_ready((data, p))
        if not parity_checked:
            # On-chip byte check ON THE MEASUREMENT SHAPE (bytes first,
            # then speed) — riding the already-compiled step saves a
            # separate small-shape remote compile (~30 s cold).  The warm
            # step patched stripe 0's first 128 bytes with p^1 = 0x01.
            stripe0 = host[0].copy()
            stripe0[0, :128] = 1
            want = gf_matmul(gfm, stripe0)
            got = np.asarray(p[0])
            if not np.array_equal(got, want):
                clog("PARITY MISMATCH vs host oracle")
                sys.exit(4)
            clog("on-chip parity vs host oracle OK")
            parity_checked = True
        t0 = time.perf_counter()
        for _ in range(n):
            data, p = step(data, p)
        jax.block_until_ready((data, p))
        _ = np.asarray(p[0, 0, :8])
        elapsed = time.perf_counter() - t0
        del data, p
        return batch * k * chunk * n / elapsed / 1e9

    def _run_multichip(mc_base_batch: int) -> None:
        """Multichip stage (ISSUE 6): shard the aggregated launch over
        the device mesh, verify bytes through the SHIPPING sharded
        dispatch, and measure AGGREGATE GB/s alongside the per-chip
        number.  Prints its own `{"multichip": ...}` JSON line; any fault
        is recorded there and never takes down the child."""
        mc: dict = {}
        try:
            n_dev = len(jax.devices())
            mc["devices"] = n_dev
            if n_dev < 2:
                mc["skipped"] = "single device"
                raise _McDone()
            from ceph_tpu.ops.dispatch import SHARDED_LAUNCHES
            from ceph_tpu.parallel import dispatch as shard_dispatch
            from ceph_tpu.parallel.sharded import _stripe_sharding

            watchdog.stage("multichip_warmup", PROBE_TIMEOUT_S)
            # Bytes first, through the SHIPPING sharded dispatch: an
            # eager encode_array above the shard threshold must register
            # one sharded launch and match the host oracle.
            shard_dispatch.configure(min_batch=n_dev, devices=0)
            mc_probe = rng.integers(0, 256, (2 * n_dev, k, 8192), dtype=np.uint8)
            s0 = SHARDED_LAUNCHES.snapshot()["launches"]
            mc_par = np.asarray(encode_fn(mc_probe))
            if SHARDED_LAUNCHES.snapshot()["launches"] != s0 + 1:
                clog("MULTICHIP: dispatch did not shard (policy/mesh fault)")
                mc["error"] = "dispatch did not shard"
                raise _McDone()
            if not np.array_equal(mc_par[0], gf_matmul(gfm, mc_probe[0])):
                clog("MULTICHIP PARITY MISMATCH vs host oracle")
                mc["error"] = "sharded parity mismatch"
                raise _McDone()
            clog(f"multichip probe OK: 1 sharded launch over {n_dev} devices")

            # Aggregate throughput: the same serial-chain methodology as
            # the per-chip number, but the arrays live stripe-sharded
            # over the mesh — each device runs the per-chip workload
            # concurrently, so input bytes/elapsed is honest aggregate.
            mc_batch = mc_base_batch * n_dev
            mesh = shard_dispatch.shard_mesh(mc_batch)  # the locked public path
            if mesh is None:
                mc["error"] = "shard policy returned no mesh"
                raise _McDone()
            sharding = _stripe_sharding(mesh)
            mc_host = rng.integers(0, 256, (mc_batch, k, chunk), dtype=np.uint8)
            mc_data = jax.device_put(mc_host, sharding)
            mc_p = jax.device_put(
                np.zeros((mc_batch, m, chunk), np.uint8), sharding
            )
            mc_data, mc_p = step(mc_data, mc_p)  # compile + warm, sharded
            jax.block_until_ready((mc_data, mc_p))
            watchdog.disarm()
            mc_iters = max(4, iters // 2)
            clog(f"multichip measuring: batch={mc_batch} iters={mc_iters} "
                 f"over {n_dev} devices")
            t0 = time.perf_counter()
            for _ in range(mc_iters):
                mc_data, mc_p = step(mc_data, mc_p)
            jax.block_until_ready((mc_data, mc_p))
            _ = np.asarray(mc_p[0, 0, :8])
            elapsed = time.perf_counter() - t0
            del mc_data, mc_p
            mc["encode_gbps"] = mc_batch * k * chunk * mc_iters / elapsed / 1e9
            mc["batch"] = mc_batch
            clog(f"multichip encode: {mc['encode_gbps']:.3f} GB/s aggregate")

            # Decode twin: chained sharded decode at the same geometry.
            try:
                erasures = [0, 5, 9]
                idx = ec.decode_index(erasures)
                watchdog.stage("multichip_decode", PROBE_TIMEOUT_S)
                d_host = rng.integers(
                    0, 256, (mc_batch, k, chunk), dtype=np.uint8
                )
                d_data = jax.device_put(d_host, sharding)
                surv = jnp.concatenate(
                    [d_data, encode_fn(d_data)], axis=1)[:, idx, :]
                del d_data
                r = jax.device_put(
                    np.zeros((mc_batch, len(erasures), chunk), np.uint8),
                    sharding,
                )

                @functools.partial(jax.jit, donate_argnums=(0,))
                def mc_dstep(s, r):
                    patch = (r[:1, :1, :128] ^ jnp.uint8(1)).reshape(1, 1, 128)
                    s2 = jax.lax.dynamic_update_slice(s, patch, (0, 0, 0))
                    return s2, ec.decode_array(erasures, s2)

                surv, r = mc_dstep(surv, r)  # compile + warm
                jax.block_until_ready((surv, r))
                watchdog.disarm()
                t0 = time.perf_counter()
                for _ in range(mc_iters):
                    surv, r = mc_dstep(surv, r)
                jax.block_until_ready((surv, r))
                _ = np.asarray(r[0, 0, :8])
                elapsed = time.perf_counter() - t0
                del surv, r
                mc["decode_gbps"] = (
                    mc_batch * k * chunk * mc_iters / elapsed / 1e9
                )
                clog(f"multichip decode: {mc['decode_gbps']:.3f} GB/s aggregate")
            except Exception as e:  # encode aggregate survives a decode fault
                watchdog.disarm()
                mc["decode_error"] = repr(e)
                clog(f"multichip decode failed: {e!r}")
        except _McDone:
            watchdog.disarm()
        except Exception as e:  # the stage must never take down the child
            watchdog.disarm()
            mc["error"] = repr(e)
            clog(f"multichip stage failed: {e!r}")
        print(json.dumps({"multichip": mc}), flush=True)

    if mc_only:
        _run_multichip(batch_candidates[0])
        return

    batch = batch_candidates[0]
    if len(batch_candidates) > 1:
        probes = {}
        for cand in batch_candidates:
            clog(f"tuning: probing batch={cand}")
            try:
                probes[cand] = run_chain(cand, 6)
            except Exception as e:
                # a failing depth (OOM, compile error) must not cost the
                # TPU headline: keep whatever candidates survive
                clog(f"tuning: batch={cand} FAILED: {e!r}")
                continue
            clog(f"tuning: batch={cand} -> {probes[cand]:.2f} GB/s")
        if probes:
            batch = max(probes, key=probes.get)

    clog(f"measuring: batch={batch} iters={iters}")
    gbps = run_chain(batch, iters)
    clog(f"done: {gbps:.3f} GB/s at batch={batch}")

    # Per-stage breakdown (one un-chained encode, stages serialized with
    # block_until_ready): attributes the headline to H2D staging, kernel,
    # or D2H readback instead of a single number.  On TPU this reuses the
    # PROBE shape — already compiled during warm-up — because a fresh
    # standalone compile at the tuned geometry (~30 s through the remote
    # compiler) after the measurement could blow the child deadline and
    # discard a perfectly good result; on CPU compiles are cheap, so the
    # breakdown runs at the measured geometry.  Guarded: losing the
    # breakdown must never lose the headline.
    stages = None
    try:
        stage_shape = (1, k, 8192) if on_tpu else (batch, k, chunk)
        clog(f"sampling per-stage breakdown (h2d/kernel/d2h) at {stage_shape}")
        stage_in = rng.integers(0, 256, stage_shape, dtype=np.uint8)
        # warm the standalone-encode compile at this shape (the measured
        # chain compiled it fused inside `step`) so it is steady-state
        jax.block_until_ready(encode_fn(jax.device_put(stage_in)))
        t0 = time.perf_counter()
        stage_dev = jax.block_until_ready(jax.device_put(stage_in))
        t1 = time.perf_counter()
        stage_par = jax.block_until_ready(encode_fn(stage_dev))
        t2 = time.perf_counter()
        _ = np.asarray(stage_par)
        t3 = time.perf_counter()
        stages = {
            "h2d_s": round(t1 - t0, 6),
            "kernel_s": round(t2 - t1, 6),
            "d2h_s": round(t3 - t2, 6),
            "shape": list(stage_shape),
        }
        clog(f"stages: {stages}")
    except Exception as e:  # headline survives a failed breakdown
        clog(f"stage breakdown failed: {e!r}")
    # Decode stage: same RS(8,3) geometry, three erasures (two data + one
    # parity) — the recovery/degraded-read-shaped workload (ISSUE 5).
    # The warm-up probe and the chain compile run under their own
    # watchdog allowances so a backend that survives encode but wedges on
    # the decode kernel family fails fast with rc=5 (attributable stage
    # in stderr) instead of silently eating the child deadline.  Bytes
    # first: the probe reconstruction is checked against the host GF
    # oracle before anything is timed.  Throughput counts survivor input
    # bytes per second, symmetrical with the encode metric.
    decode_result = None
    decode_err = ""
    try:
        erasures = [0, 5, 9]
        idx = ec.decode_index(erasures)
        watchdog.stage("decode_probe", PROBE_TIMEOUT_S)
        clog(f"decode probe: erasures {erasures}, survivors {idx}")
        # probe shape reuses the encode probe's compiled parity kernel;
        # only the decode coder itself compiles here (seconds-scale)
        probe_full = np.concatenate([probe_in[0], gf_matmul(gfm, probe_in[0])])
        probe_surv = jnp.concatenate(
            [jnp.asarray(probe_in), encode_fn(jnp.asarray(probe_in))], axis=1
        )[:, idx, :]
        probe_rec = np.asarray(ec.decode_array(erasures, probe_surv))
        if not np.array_equal(probe_rec[0], probe_full[erasures]):
            clog("DECODE PROBE MISMATCH vs host oracle")
            sys.exit(4)
        clog("decode probe vs host oracle OK")

        # Serial-chain methodology, mirroring the encode loop: each
        # launch's survivors depend on the previous reconstruction.
        @functools.partial(jax.jit, donate_argnums=(0,))
        def dstep(s, r):
            patch = (r[:1, :1, :128] ^ jnp.uint8(1)).reshape(1, 1, 128)
            s2 = jax.lax.dynamic_update_slice(s, patch, (0, 0, 0))
            return s2, ec.decode_array(erasures, s2)

        watchdog.stage("decode_warmup", PROBE_TIMEOUT_S)
        clog(f"decode warm-up at batch={batch}")
        d_host = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
        d_data = jnp.asarray(d_host)
        surv = jnp.concatenate([d_data, encode_fn(d_data)], axis=1)[:, idx, :]
        del d_data
        r = jnp.zeros((batch, len(erasures), chunk), jnp.uint8)
        surv, r = dstep(surv, r)  # compile + warm
        jax.block_until_ready((surv, r))
        watchdog.disarm()
        d_iters = iters
        clog(f"decode measuring: batch={batch} iters={d_iters}")
        t0 = time.perf_counter()
        for _ in range(d_iters):
            surv, r = dstep(surv, r)
        jax.block_until_ready((surv, r))
        _ = np.asarray(r[0, 0, :8])
        d_elapsed = time.perf_counter() - t0
        d_gbps = batch * k * chunk * d_iters / d_elapsed / 1e9
        del surv, r
        clog(f"decode done: {d_gbps:.3f} GB/s at batch={batch}")
        decode_result = {"gbps": d_gbps, "batch": batch, "parity_ok": True}
        # per-stage h2d/kernel/d2h breakdown for the decode launch,
        # guarded like the encode one: losing the breakdown must never
        # lose the decode (or encode) headline
        try:
            # host-side copy staged BEFORE the timing window, so h2d_s
            # times only the put (symmetry with the encode breakdown)
            host_surv = np.asarray(probe_surv)
            jax.block_until_ready(ec.decode_array(erasures, jax.device_put(probe_surv)))
            t0 = time.perf_counter()
            d_dev = jax.block_until_ready(jax.device_put(host_surv))
            t1 = time.perf_counter()
            d_rec = jax.block_until_ready(ec.decode_array(erasures, d_dev))
            t2 = time.perf_counter()
            _ = np.asarray(d_rec)
            t3 = time.perf_counter()
            decode_result["stages"] = {
                "h2d_s": round(t1 - t0, 6),
                "kernel_s": round(t2 - t1, 6),
                "d2h_s": round(t3 - t2, 6),
                "shape": list(probe_surv.shape),
            }
            clog(f"decode stages: {decode_result['stages']}")
        except Exception as e:
            clog(f"decode stage breakdown failed: {e!r}")
    except SystemExit:
        raise
    except Exception as e:  # encode headline survives a failed decode stage
        watchdog.disarm()
        decode_err = repr(e)
        clog(f"decode stage failed: {decode_err}")

    # Verify stage (ISSUE 9): the deep-scrub compare-only kernel at the
    # same RS(8,3) geometry — full (batch, k+m, chunk) codewords in, a
    # per-stripe mismatch bitmap out.  Bytes first: the probe bitmap is
    # checked against the pure-numpy host oracle (clean codewords AND a
    # corrupted shard) before anything is timed.  Throughput counts full
    # codeword input bytes per second — what a continuous background
    # integrity sweep actually pushes through the chip.
    verify_result = None
    verify_err = ""
    try:
        watchdog.stage("verify_probe", PROBE_TIMEOUT_S)
        clog("verify probe: bitmap vs host oracle")
        probe_cw = np.concatenate(
            [probe_in, np.asarray(encode_fn(jnp.asarray(probe_in)))], axis=1
        )
        probe_bm = np.asarray(ec.verify_array(probe_cw))
        if not np.array_equal(probe_bm, ec.verify_array_host(probe_cw)):
            clog("VERIFY PROBE MISMATCH vs host oracle")
            sys.exit(4)
        if probe_bm.any():
            clog("VERIFY PROBE: clean codeword flagged inconsistent")
            sys.exit(4)
        bad_cw = probe_cw.copy()
        bad_cw[0, 3, 11] ^= 0x5A  # silent single-shard corruption
        bad_bm = np.asarray(ec.verify_array(bad_cw))
        if not np.array_equal(bad_bm, ec.verify_array_host(bad_cw)) or not bad_bm[0]:
            clog("VERIFY PROBE: corrupted shard not flagged")
            sys.exit(4)
        clog("verify probe vs host oracle OK")

        # Serial-chain methodology, mirroring the encode/decode loops:
        # each launch's codeword depends on the previous bitmap, so
        # runtime caching cannot elide repeated launches.
        @functools.partial(jax.jit, donate_argnums=(0,))
        def vstep(cw, bm):
            patch = (cw[:1, :1, :128] ^ bm[0] ^ jnp.uint8(1)).reshape(1, 1, 128)
            cw2 = jax.lax.dynamic_update_slice(cw, patch, (0, 0, 0))
            return cw2, ec.verify_array(cw2)

        watchdog.stage("verify_warmup", PROBE_TIMEOUT_S)
        clog(f"verify warm-up at batch={batch}")
        v_host = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
        v_data = jnp.asarray(v_host)
        cw = jnp.concatenate([v_data, encode_fn(v_data)], axis=1)
        del v_data
        bm = jnp.zeros((batch,), jnp.uint8)
        cw, bm = vstep(cw, bm)  # compile + warm
        jax.block_until_ready((cw, bm))
        watchdog.disarm()
        clog(f"verify measuring: batch={batch} iters={iters}")
        t0 = time.perf_counter()
        for _ in range(iters):
            cw, bm = vstep(cw, bm)
        jax.block_until_ready((cw, bm))
        _ = np.asarray(bm[:8])
        v_elapsed = time.perf_counter() - t0
        v_gbps = batch * (k + m) * chunk * iters / v_elapsed / 1e9
        del cw, bm
        clog(f"verify done: {v_gbps:.3f} GB/s at batch={batch}")
        verify_result = {"gbps": v_gbps, "batch": batch, "bitmap_ok": True}
    except SystemExit:
        raise
    except Exception as e:  # headline survives a failed verify stage
        watchdog.disarm()
        verify_err = repr(e)
        clog(f"verify stage failed: {verify_err}")

    # Pipeline stage (ISSUE 11): steady-state OVERLAPPED throughput at
    # in-flight depth 1/2/4.  Unlike the serial chain (which keeps its
    # round-over-round comparability above and never re-uploads inside
    # the loop), every iteration here pays the full end-to-end launch
    # path — fresh host bytes, H2D, kernel, bounded-ring reap — exactly
    # the aggregator's production shape; depth d lets launch N+1's H2D
    # run under launch N's kernel.  Each slot is its own serial chain
    # (the host patch mutates its input every round) so runtime-level
    # caching of repeated identical launches cannot inflate the number.
    pipeline_result = None
    pipeline_err = ""
    try:
        watchdog.stage("pipeline_warmup", PROBE_TIMEOUT_S)
        p_iters = max(8, iters)
        hosts = [
            rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
            for _ in range(4)
        ]

        # HBM ledger (ISSUE 13): the staged inputs and in-flight parity
        # are tracked in the `scratch` pool, and the per-depth PEAK is
        # folded into the JSON — bench rounds correlate throughput
        # against the memory headroom each depth costs, the number that
        # decides how far ec_tpu_pipeline_depth can be pushed
        from ceph_tpu.common.mempool import ledger as hbm_ledger
        from ceph_tpu.common.mempool import track_buffer

        hbm = hbm_ledger()

        def run_pipeline(depth: int, n: int) -> float:
            inflight = []
            # warm: one launch per slot buffer (compile already paid)
            for s in range(depth):
                jax.block_until_ready(encode_fn(jax.device_put(hosts[s])))
            t0 = time.perf_counter()
            for i in range(n):
                h = hosts[i % depth]
                h[0, 0, :8] ^= np.uint8(i + 1)  # per-slot serial chain
                par = encode_fn(track_buffer(jax.device_put(h), "scratch"))
                inflight.append(track_buffer(par, "scratch"))
                if len(inflight) >= depth:
                    inflight.pop(0).block_until_ready()
            while inflight:
                inflight.pop(0).block_until_ready()
            _ = np.asarray(par[0, 0, :8])
            elapsed = time.perf_counter() - t0
            return batch * k * chunk * n / elapsed / 1e9

        run_pipeline(1, 2)  # warm the eager-dispatch path end to end
        watchdog.disarm()
        depths = {}
        hbm_peaks = {}
        for depth in (1, 2, 4):
            watchdog.stage(f"pipeline_depth_{depth}", PROBE_TIMEOUT_S)
            hbm.reset_peaks()
            depths[depth] = run_pipeline(depth, p_iters)
            hbm_peaks[str(depth)] = hbm.peak_total_bytes()
            clog(
                f"pipeline depth={depth}: {depths[depth]:.3f} GB/s "
                f"(hbm peak {hbm_peaks[str(depth)]} B)"
            )
            watchdog.disarm()
        best_depth = max(depths, key=depths.get)
        overlap = max(0.0, 1.0 - depths[1] / depths[best_depth])
        pipeline_result = {
            "depths": {str(d): round(g, 3) for d, g in depths.items()},
            "best_depth": best_depth,
            "gbps": depths[best_depth],
            "overlap_fraction": round(overlap, 4),
            "batch": batch,
            "hbm_peak_bytes": hbm_peaks,
        }
        clog(
            f"pipeline best: depth={best_depth} "
            f"{depths[best_depth]:.3f} GB/s (overlap {overlap:.2%})"
        )
        # Device-cache witness (ISSUE 11 acceptance): a chunk served
        # from the device-resident cache must skip the H2D leg — the
        # flight record of the hit carries d2h only, h2d_s == 0.
        from ceph_tpu.ops.device_cache import DeviceChunkCache
        from ceph_tpu.ops.flight_recorder import flight_recorder

        cc = DeviceChunkCache(max_bytes=8 << 20)
        chunk_bytes = rng.integers(0, 256, 64 * 1024, dtype=np.uint8)
        assert cc.put("bench/obj", 0, 1, chunk_bytes)
        served = cc.fetch_many("bench/obj", [0], 1, length=chunk_bytes.nbytes)
        assert served is not None and np.array_equal(
            served[0], chunk_bytes
        ), "device-cache hit returned wrong bytes"
        hit_recs = [
            r for r in flight_recorder().records()
            if r["flags"].get("cache_hit")
        ]
        assert hit_recs and hit_recs[-1]["h2d_s"] == 0.0, (
            "cache-hit flight record must carry no H2D span"
        )
        pipeline_result["device_cache"] = {
            "hit_skipped_h2d": True,
            "d2h_s": round(hit_recs[-1]["d2h_s"], 6),
            **cc.perf_dump(),
        }
    except SystemExit:
        raise
    except Exception as e:  # headline survives a failed pipeline stage
        watchdog.disarm()
        pipeline_err = repr(e)
        clog(f"pipeline stage failed: {pipeline_err}")
        if pipeline_result is not None:
            # the depths were already measured, so the pipelined block
            # ships — but the failure (the device-cache witness runs
            # after the result is built) must be machine-visible in the
            # JSON, not just a clog line
            pipeline_result["error"] = pipeline_err
            pipeline_result.setdefault(
                "device_cache", {"hit_skipped_h2d": False}
            )

    # Super-launch fusion stage (ISSUE 18): the AGGREGATED data path
    # under a multi-submitter backlog — the production shape fusion
    # exists for.  N submitter threads race sub-batches through one
    # EncodeAggregator whose in-flight ring is kept full, so window
    # trips defer and whole windows launch as ONE fused dispatch
    # (ec_tpu_fuse_max_windows).  Unlike the single-thread pipeline
    # stage above, every byte here also pays the aggregator's
    # concatenate + per-group parity settle — this is end-to-end
    # aggregated throughput, not a kernel number.
    fused_result = None
    fused_err = ""
    try:
        watchdog.stage("fused_warmup", PROBE_TIMEOUT_S)
        import threading

        from ceph_tpu.codec.matrix_codec import EncodeAggregator

        try:
            f_threads = max(1, int(os.environ.get("BENCH_FUSED_THREADS", "4")))
        except ValueError:
            clog("ignoring malformed BENCH_FUSED_THREADS")
            f_threads = 4
        f_sub = max(1, batch // 4)
        f_window = 4
        agg = EncodeAggregator(
            window=f_window,
            max_bytes=1 << 30,
            inflight_max_bytes=1 << 30,
            pipeline_depth=2,
            fuse_max_windows=4,
        )
        f_tickets = max(16, 4 * iters)  # per thread, per pass
        per_thread = [
            [
                rng.integers(0, 256, (f_sub, k, chunk), dtype=np.uint8)
                for _ in range(4)
            ]
            for _ in range(f_threads)
        ]
        f_errs: list[BaseException] = []

        def f_worker(t: int, n: int) -> None:
            try:
                pend = []
                for i in range(n):
                    h = per_thread[t][i % 4]
                    # per-slot serial chain, as in the pipeline stage:
                    # identical-launch elision cannot inflate the number
                    h[0, 0, :8] ^= np.uint8((t * 31 + i) % 255 + 1)
                    pend.append(agg.submit(ec, h))
                    # lag the reaps so the ring stays full — the backlog
                    # is what arms the window-trip deferral
                    if len(pend) > 2 * f_window:
                        np.asarray(pend.pop(0))
                for p in pend:
                    np.asarray(p)
            except BaseException as e:
                f_errs.append(e)

        def f_pass(n: int) -> float:
            threads = [
                threading.Thread(target=f_worker, args=(t, n), daemon=True)
                for t in range(f_threads)
            ]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            agg.flush()
            elapsed = time.perf_counter() - t0
            if f_errs:
                raise f_errs[0]
            return f_threads * n * f_sub * k * chunk / elapsed / 1e9

        clog(
            f"fused warm-up: {f_threads} submitters x sub_batch={f_sub} "
            f"window={f_window}"
        )
        # warm pass: compiles the fused launch shapes (each fused window
        # count is its own jit geometry) outside the measured window
        f_pass(max(8, f_tickets // 4))
        watchdog.disarm()
        f_gbps = 0.0
        launches = fused_launches = fused_windows = 0
        for p in range(2):
            watchdog.stage(f"fused_pass_{p}", PROBE_TIMEOUT_S)
            l0 = agg.perf.get("launches")
            fl0 = agg.perf.get("fused_launches")
            fw0 = agg.perf.get("fused_windows")
            pass_gbps = f_pass(f_tickets)
            if pass_gbps > f_gbps:
                f_gbps = pass_gbps
                launches = int(agg.perf.get("launches") - l0)
                fused_launches = int(agg.perf.get("fused_launches") - fl0)
                fused_windows = int(agg.perf.get("fused_windows") - fw0)
            clog(f"fused pass {p}: {pass_gbps:.3f} GB/s")
            watchdog.disarm()
        windows_dispatched = f_threads * f_tickets // f_window
        clog(
            f"fused done: {f_gbps:.3f} GB/s "
            f"({fused_launches}/{launches} launches fused, "
            f"{fused_windows} windows over {windows_dispatched} dispatched)"
        )
        fused_result = {
            "gbps": f_gbps,
            "threads": f_threads,
            "sub_batch": f_sub,
            "window": f_window,
            "launches": launches,
            "fused_launches": fused_launches,
            "fused_windows": fused_windows,
            "windows_dispatched": windows_dispatched,
        }
    except SystemExit:
        raise
    except Exception as e:  # headline survives a failed fused stage
        watchdog.disarm()
        fused_err = repr(e)
        clog(f"fused stage failed: {fused_err}")

    # Padding-waste stage (ISSUE 18): a mixed-size workload through a
    # bucketed aggregator.  The first passes pay the static pow2/64
    # rounding; the _PadBuckets learner promotes each recurring batch
    # size to an exact-fit launch target, so the LAST pass's waste
    # ratio is the learned steady state — reported next to the analytic
    # pow2 baseline the same sizes would have paid forever.
    waste_result = None
    waste_err = ""
    try:
        watchdog.stage("pad_waste", PROBE_TIMEOUT_S)
        from ceph_tpu.codec.matrix_codec import EncodeAggregator

        wagg = EncodeAggregator(
            window=2,
            max_bytes=1 << 30,
            inflight_max_bytes=1 << 30,
            pipeline_depth=0,
            fuse_max_windows=1,  # isolate the learner from fusion
            pad_buckets=4,
        )
        w_chunk = 32 * 1024
        w_sizes = (5, 12, 23, 51)  # pairs -> group stripes 10/24/46/102
        pow2_pad = sum(wagg._pad_target(2 * s) - 2 * s for s in w_sizes)
        pow2_baseline = pow2_pad / (
            pow2_pad + sum(2 * s for s in w_sizes)
        )
        w_hosts = {
            s: rng.integers(0, 256, (s, k, w_chunk), dtype=np.uint8)
            for s in w_sizes
        }
        w_ratio = pow2_baseline
        for wp in range(4):
            pad0 = wagg.perf.get("pad_stripes")
            w_stripes = 0
            w_tickets = []
            for s in w_sizes:
                for _ in range(2):  # one window = 2 same-size tickets
                    w_tickets.append(wagg.submit(ec, w_hosts[s]))
                    w_tickets.append(wagg.submit(ec, w_hosts[s]))
                    w_stripes += 2 * s
            wagg.flush()
            for t in w_tickets:
                np.asarray(t)
            w_pad = wagg.perf.get("pad_stripes") - pad0
            w_ratio = w_pad / (w_pad + w_stripes)
            clog(f"pad_waste pass {wp}: ratio {w_ratio:.4f}")
        watchdog.disarm()
        clog(
            f"pad_waste done: learned {w_ratio:.4f} "
            f"vs pow2 baseline {pow2_baseline:.4f}"
        )
        waste_result = {
            "ratio": round(w_ratio, 6),
            "pow2_baseline": round(pow2_baseline, 6),
            "sizes": list(w_sizes),
        }
    except SystemExit:
        raise
    except Exception as e:  # headline survives a failed waste stage
        watchdog.disarm()
        waste_err = repr(e)
        clog(f"pad_waste stage failed: {waste_err}")

    # Checksum stage (ISSUE 20): BlueStore per-block crc32c as packed
    # bit-matrix matmuls through the offload runtime's device kernel.
    # Bytes first: the probe digests are checked against utils/crc32c
    # itself (the host oracle the fallback path IS) before anything is
    # timed.  Each measured round mutates the block batch with the round
    # index, so a fresh H2D + launch is paid every iteration — runtime
    # caching of repeated identical launches cannot inflate the number.
    csum_result = None
    csum_err = ""
    CS_BLOCK = 4096
    cs_batch = 4096 if on_tpu else 512  # blocks per launch
    try:
        watchdog.stage("csum_probe", PROBE_TIMEOUT_S)
        from ceph_tpu.ops.checksum_offload import (
            crc32c_device,
            crc32c_host_rows,
        )

        clog("csum probe: device digests vs utils/crc32c host oracle")
        cs_probe = rng.integers(0, 256, (64, CS_BLOCK), dtype=np.uint8)
        if not np.array_equal(
            np.asarray(crc32c_device(cs_probe)), crc32c_host_rows(cs_probe)
        ):
            clog("CSUM PROBE MISMATCH vs utils/crc32c host oracle")
            sys.exit(4)
        # ragged tail length too: compressed stored forms are not
        # BLOCK-sized, and the matrix cache must be right for every L
        cs_tail = rng.integers(0, 256, (16, 1000), dtype=np.uint8)
        if not np.array_equal(
            np.asarray(crc32c_device(cs_tail)), crc32c_host_rows(cs_tail)
        ):
            clog("CSUM PROBE MISMATCH at ragged tail length")
            sys.exit(4)
        clog("csum probe vs host oracle OK")

        watchdog.stage("csum_warmup", PROBE_TIMEOUT_S)
        cs_blocks = rng.integers(
            0, 256, (cs_batch, CS_BLOCK), dtype=np.uint8
        )
        crcs = crc32c_device(cs_blocks)
        jax.block_until_ready(crcs)
        watchdog.disarm()
        clog(f"csum measuring: blocks={cs_batch} iters={iters}")
        t0 = time.perf_counter()
        for i in range(iters):
            cs_blocks[0, :4] ^= np.uint8(i + 1)  # fresh bytes each round
            crcs = crc32c_device(cs_blocks)
        jax.block_until_ready(crcs)
        _ = np.asarray(crcs[:8])
        cs_elapsed = time.perf_counter() - t0
        cs_gbps = cs_batch * CS_BLOCK * iters / cs_elapsed / 1e9
        clog(f"csum done: {cs_gbps:.3f} GB/s at blocks={cs_batch}")
        csum_result = {
            "gbps": cs_gbps,
            "blocks": cs_batch,
            "block_bytes": CS_BLOCK,
            "digest_ok": True,
        }
    except SystemExit:
        raise
    except Exception as e:  # headline survives a failed csum stage
        watchdog.disarm()
        csum_err = repr(e)
        clog(f"csum stage failed: {csum_err}")

    # Write-path offload stage (ISSUE 20): the full offloaded BlueStore
    # large-write device work — the compressor's byte-plane transpose +
    # zero-run-elision transform AND the per-block crc32c — over the
    # same block batch per round.  Probe checks the device transform
    # byte-identical to the host transform (the fallback IS the host
    # transform) before timing; throughput counts raw input bytes once.
    offload_result = None
    offload_err = ""
    try:
        watchdog.stage("compress_probe", PROBE_TIMEOUT_S)
        from ceph_tpu.compressor.device import (
            transform_rows,
            transform_rows_device,
        )

        clog("compress probe: device transform vs host oracle")
        off_probe = rng.integers(0, 256, (32, CS_BLOCK), dtype=np.uint8)
        off_probe[:, ::2] = 0  # zero-heavy planes: elision has work to do
        if not np.array_equal(
            np.asarray(transform_rows_device(off_probe)),
            transform_rows(off_probe),
        ):
            clog("COMPRESS PROBE MISMATCH vs host transform oracle")
            sys.exit(4)
        clog("compress probe vs host oracle OK")

        watchdog.stage("offload_warmup", PROBE_TIMEOUT_S)
        off_blocks = rng.integers(
            0, 256, (cs_batch, CS_BLOCK), dtype=np.uint8
        )
        off_blocks[:, 1::2] = 0
        t = transform_rows_device(off_blocks)
        c = crc32c_device(off_blocks)
        jax.block_until_ready((t, c))
        watchdog.disarm()
        clog(f"offload measuring: blocks={cs_batch} iters={iters}")
        t0 = time.perf_counter()
        for i in range(iters):
            off_blocks[0, :4] ^= np.uint8(i + 1)  # fresh bytes each round
            t = transform_rows_device(off_blocks)
            c = crc32c_device(off_blocks)
        jax.block_until_ready((t, c))
        _ = np.asarray(c[:8])
        off_elapsed = time.perf_counter() - t0
        off_gbps = cs_batch * CS_BLOCK * iters / off_elapsed / 1e9
        del t, c
        clog(f"offload done: {off_gbps:.3f} GB/s at blocks={cs_batch}")
        offload_result = {
            "gbps": off_gbps,
            "blocks": cs_batch,
            "block_bytes": CS_BLOCK,
            "transform_ok": True,
        }
    except SystemExit:
        raise
    except Exception as e:  # headline survives a failed offload stage
        watchdog.disarm()
        offload_err = repr(e)
        clog(f"offload stage failed: {offload_err}")

    result = {
        "platform": got,
        "gbps": gbps,
        "batch": batch,
        "parity_ok": True,
        "probe_s": round(probe_s, 3),
    }
    # degraded-backend verdict (ISSUE 7 device guard): a run whose
    # launches fell back to the host oracle must say so, or a silently
    # degraded chip reads as a kernel regression in the headline number
    from ceph_tpu.ops import dispatch as ec_dispatch
    from ceph_tpu.ops.guard import device_guard

    fallbacks = ec_dispatch.FALLBACK_LAUNCHES.snapshot()["launches"]
    if device_guard().degraded or fallbacks:
        result["backend_degraded"] = bool(device_guard().degraded)
        result["fallback_launches"] = fallbacks
    if decode_result is not None:
        result["decode"] = decode_result
    elif decode_err:
        result["decode_error"] = decode_err
    if verify_result is not None:
        result["verify"] = verify_result
    elif verify_err:
        result["verify_error"] = verify_err
    if pipeline_result is not None:
        result["pipeline"] = pipeline_result
    elif pipeline_err:
        result["pipeline_error"] = pipeline_err
    if fused_result is not None:
        result["fused"] = fused_result
    elif fused_err:
        result["fused_error"] = fused_err
    if waste_result is not None:
        result["pad_waste"] = waste_result
    elif waste_err:
        result["pad_waste_error"] = waste_err
    if csum_result is not None:
        result["csum"] = csum_result
    elif csum_err:
        result["csum_error"] = csum_err
    if offload_result is not None:
        result["offload"] = offload_result
    elif offload_err:
        result["offload_error"] = offload_err
    if stages is not None:
        result["stages"] = stages
    if os.environ.get("BENCH_TRACE"):
        # One traced encode OUTSIDE the measured loop (BENCH_TRACE=1):
        # per-stage spans (h2d / kernel_launch / kernel_wait+d2h from
        # codec/tracing.py) so a regression in the headline number is
        # attributable to a stage, not just observed end to end.
        from ceph_tpu.common import tracer as tracer_mod
        from ceph_tpu.common.tracer import Tracer

        clog("BENCH_TRACE: sampling one traced encode")
        tr = Tracer("bench", enabled=True)
        root = tr.start_span("bench:encode")
        root.keyval("batch", 2)
        with tracer_mod.span_scope(root):
            traced = ec.encode_array(
                rng.integers(0, 256, (2, k, chunk), dtype=np.uint8)
            )
            with root.child("kernel_wait+d2h"):
                np.asarray(traced)
        root.finish()
        result["trace"] = [
            {
                "name": s["name"],
                "parent_id": s["parent_id"],
                "span_id": s["span_id"],
                "ms": None
                if s["end"] is None
                else round((s["end"] - s["start"]) * 1e3, 3),
            }
            for s in tr.export()
        ]
    # Flight-recorder summary (ISSUE 8): launch count, mean queue-wait,
    # occupancy over the child's run.  Bench encodes run OUTSIDE the
    # aggregators, so these are span-less dispatch-shape witnesses
    # (occupancy 0 here is expected); the aggregated data-path numbers
    # come from the OSD asok dump_flight / chaos report instead.
    try:
        from ceph_tpu.ops.flight_recorder import flight_recorder

        result["flight"] = flight_recorder().summary()
    except Exception as e:  # headline survives a summary fault
        clog(f"flight summary failed: {e!r}")
    # The per-chip headline is SAFE from here on: it goes out before the
    # multichip stage runs, and the parent merges every JSON line it can
    # salvage — a multichip hang/crash can only lose the multichip twin.
    print(json.dumps(result), flush=True)
    if platform == "tpu":
        # Real chips don't share a threadpool, so the multichip stage can
        # ride the same child (one backend init, one warm codec); on CPU
        # the parent spawns a separate forced-8-device child instead.
        _run_multichip(batch)


class _McDone(Exception):
    """Early exit from the multichip stage (skip/fault already recorded)."""


def classify_tpu_failure(
    rc: int | None, deadline: bool, stage: str | None
) -> str:
    """TPU-child failure taxonomy (ISSUE 8 satellite): collapse the
    rc/deadline/watchdog-stage evidence into one machine-diffable cause
    so the round-over-round fallback pattern (rounds 4-5 fell back on
    backend-init hangs) is comparable across BENCH_r*.json without
    parsing prose.

    - `import_hang`:       the import_jax watchdog stage overran (the
                           axon sitecustomize blocking in `import jax`)
    - `backend_init_hang`: jax.devices() overran its ~45 s sub-deadline
                           (rc=6; the parent retries this once)
    - `stage_hang`:        any later watchdog stage overran (rc=5), or
                           the whole child hit the parent deadline
    - `device_error`:      the child FAILED rather than hung — no TPU
                           (rc=3), parity mismatch (rc=4), a crash, or
                           an exit (even rc=0) without a usable result
    """
    if stage == "import_jax":
        return "import_hang"
    if rc == 6 or stage == "backend_init":
        return "backend_init_hang"
    if rc == 5 or stage is not None or deadline:
        return "stage_hang"
    return "device_error"


def _child_env(platform: str, multichip: bool = False) -> dict:
    """Environment for a measurement child.

    The TPU child must not inherit CPU-forcing left by earlier callers in the
    same process tree (dryrun_multichip sets JAX_PLATFORMS=cpu process-wide;
    conftest adds xla_force_host_platform_device_count to XLA_FLAGS).
    """
    env = dict(os.environ)
    if platform == "tpu":
        env.pop("JAX_PLATFORMS", None)
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        if flags:
            env["XLA_FLAGS"] = " ".join(flags)
        else:
            env.pop("XLA_FLAGS", None)
    else:
        # The axon sitecustomize registers its PJRT plugin in EVERY python
        # process (gated on PALLAS_AXON_POOL_IPS) and that registration
        # blocks in `import jax` when the tunnel is wedged.  The CPU
        # fallback child must stay alive precisely when the TPU path is
        # broken, so strip the gate variable and force the CPU platform.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        if multichip:
            # Simulated 8-device mesh for the multichip-only CPU child
            # (the dryrun recipe): proves the sharded launch path and
            # emits the aggregate metric.  Virtual devices share the
            # host's cores, so the CPU aggregate is a plumbing witness,
            # not a scaling claim; a pre-set count is honored.
            devs = os.environ.get("BENCH_CPU_DEVICES", "8")
            if "xla_force_host_platform_device_count" not in env.get(
                "XLA_FLAGS", ""
            ):
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count={devs}"
                ).strip()
    return env


def _parse_result_lines(stdout: bytes, require: str = "gbps") -> dict | None:
    """Merge every JSON line the child printed (base result first, then
    the optional `{"multichip": ...}` trailer) into one dict.  None when
    no line carried the `require` key (the stage that makes the child's
    output usable: the base measurement, or `multichip` for the
    multichip-only child)."""
    merged: dict = {}
    for line in stdout.decode(errors="replace").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            merged.update(json.loads(line))
        except json.JSONDecodeError:
            continue
    return merged if require in merged else None


def _failure_info(
    platform: str, stdout: bytes, rc: int | None, deadline: bool, detail: str
) -> dict:
    """Structured failure record for the emitted JSON (the taxonomy
    satellite): cause + watchdog stage (when the child reported one) +
    the raw detail string."""
    merged = _parse_result_lines(stdout, require="failure_stage") or {}
    stage = merged.get("failure_stage")
    info = {
        "cause": classify_tpu_failure(rc, deadline, stage),
        "detail": detail,
    }
    if stage is not None:
        info["stage"] = stage
    if rc is not None:
        info["rc"] = rc
    return info


def _try_platform(
    platform: str, deadline: float
) -> tuple[dict | None, str, dict | None]:
    """Run a measurement child; return (result dict or None, error
    string, failure-taxonomy dict or None).

    The child streams one JSON line per completed stage, so a late-stage
    hang or watchdog kill (multichip after the headline) SALVAGES every
    stage that finished instead of discarding the whole child."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child", platform]
    _log(f"spawning {platform} child (deadline {deadline:.0f}s)")
    try:
        proc = subprocess.run(
            cmd,
            stdout=subprocess.PIPE,
            stderr=None,  # child progress flows straight to our stderr
            timeout=deadline,
            env=_child_env(platform),
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as e:
        result = _parse_result_lines(e.stdout or b"")
        if result is not None:
            _log(f"{platform} child hit the deadline AFTER the headline; "
                 "salvaging completed stages")
            return result, "", None
        detail = f"{platform} child hit {deadline:.0f}s deadline (backend hang?)"
        return None, detail, _failure_info(
            platform, e.stdout or b"", None, True, detail
        )
    if proc.returncode != 0:
        result = _parse_result_lines(proc.stdout)
        if result is not None:
            _log(f"{platform} child exited rc={proc.returncode} AFTER the "
                 "headline; salvaging completed stages")
            return result, "", None
        detail = f"{platform} child exited rc={proc.returncode}"
        return None, detail, _failure_info(
            platform, proc.stdout, proc.returncode, False, detail
        )
    result = _parse_result_lines(proc.stdout)
    if result is not None:
        return result, "", None
    detail = f"{platform} child produced no JSON result"
    return None, detail, _failure_info(platform, proc.stdout, 0, False, detail)


def _try_multichip_cpu(deadline: float) -> dict | None:
    """Run the multichip-only CPU child (forced 8 simulated devices) and
    return its `multichip` payload; None on any fault.  Separate from the
    per-chip CPU child so the virtual-device threadpool split never taxes
    the per-chip headline."""
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--child", "cpu", "--multichip-only",
    ]
    _log(f"spawning multichip CPU child (deadline {deadline:.0f}s)")
    try:
        proc = subprocess.run(
            cmd,
            stdout=subprocess.PIPE,
            stderr=None,
            timeout=deadline,
            env=_child_env("cpu", multichip=True),
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        stdout = proc.stdout
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout or b""
    merged = _parse_result_lines(stdout, require="multichip")
    return merged["multichip"] if merged is not None else None


def main() -> None:
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        run_child(sys.argv[2], mc_only="--multichip-only" in sys.argv[3:])
        return

    tpu_error = ""
    tpu_failure = None
    result = None
    init_retries = 0
    attempt = 0
    while attempt < TPU_RETRIES:
        attempt += 1
        result, err, failure = _try_platform("tpu", TPU_DEADLINE_S)
        if result is not None:
            break
        tpu_error = err
        tpu_failure = failure
        _log(f"TPU attempt {attempt}/{TPU_RETRIES} failed: {err}")
        if "deadline" in err:
            break  # a hang will hang again; don't burn another deadline
        if "rc=3" in err:
            break  # no TPU on this host — deterministic, retry can't help
        if "rc=4" in err:
            break  # parity mismatch is deterministic too — fall back
        if "rc=5" in err:
            break  # stage watchdog caught a backend hang — same story
        if "rc=6" in err:
            # backend init hang, caught by its own ~45 s sub-deadline: a
            # transient tunnel blip recovers on retry, a wedged runtime
            # fails fast again — ONE retry, riding OUTSIDE the generic
            # attempt budget so it happens even with BENCH_TPU_RETRIES=1
            # or after a generic-failure attempt, then CPU fallback with
            # most of the round's deadline intact
            init_retries += 1
            if init_retries > 1:
                break
            _log("backend init hang: retrying once before CPU fallback")
            attempt -= 1
            time.sleep(10)
            continue
        if attempt < TPU_RETRIES:
            time.sleep(10)

    if result is None:
        _log("falling back to CPU measurement")
        result, err, _cpu_failure = _try_platform("cpu", CPU_DEADLINE_S)
        if result is None:
            # Still emit a parseable line: an attributable environment fault
            # beats a traceback.
            out = {
                "metric": "rs_8_3_encode_GBps_per_chip",
                "value": 0,
                "unit": "GB/s",
                "vs_baseline": 0,
                "error": f"tpu: {tpu_error}; cpu: {err}",
            }
            if tpu_failure is not None:
                out["tpu_failure"] = tpu_failure
            print(json.dumps(out))
            sys.exit(0)

    # Multichip on the CPU fallback runs in its OWN child with a forced
    # 8-device simulated mesh (the per-chip child stays 1-device so the
    # headline is untaxed); a TPU child already ran it in-process.
    mc = result.get("multichip")
    if result.get("platform") == "cpu" and (mc is None or "skipped" in mc):
        mc = _try_multichip_cpu(CPU_DEADLINE_S)
        if mc is not None:
            result["multichip"] = mc

    gbps = result["gbps"]
    out = {
        "metric": "rs_8_3_encode_GBps_per_chip",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / NORTH_STAR_GBPS, 4),
        "platform": result["platform"],
    }
    # decode twin metric rides the same line (the driver parses one JSON
    # object): survivor-input GB/s of the recovery-shaped RS(8,3) decode
    if "decode" in result:
        d = result["decode"]
        out["decode"] = {
            "metric": "rs_8_3_decode_GBps_per_chip",
            "value": round(d["gbps"], 3),
            "unit": "GB/s",
            "vs_encode": round(d["gbps"] / gbps, 4) if gbps else 0,
        }
        if "stages" in d:
            out["decode"]["stages"] = d["stages"]
    elif "decode_error" in result:
        out["decode_error"] = result["decode_error"]
    # verify triplet metric (ISSUE 9): full-codeword GB/s of the
    # deep-scrub compare-only RS(8,3) kernel — the device-speed ceiling
    # of continuous background integrity checking
    if "verify" in result:
        v = result["verify"]
        out["verify"] = {
            "metric": "rs_8_3_verify_GBps_per_chip",
            "value": round(v["gbps"], 3),
            "unit": "GB/s",
            "vs_encode": round(v["gbps"] / gbps, 4) if gbps else 0,
        }
    elif "verify_error" in result:
        out["verify_error"] = result["verify_error"]
    # pipelined metric (ISSUE 11): steady-state overlapped end-to-end
    # throughput at the best in-flight depth, alongside (never
    # replacing) the serial-chain headline, plus the overlap fraction
    # and the device-cache skipped-H2D witness
    if "pipeline" in result:
        p = result["pipeline"]
        out["pipelined"] = {
            "metric": "rs_8_3_encode_GBps_per_chip_pipelined",
            "value": round(p["gbps"], 3),
            "unit": "GB/s",
            "best_depth": p["best_depth"],
            "depths": p["depths"],
            "overlap_fraction": p["overlap_fraction"],
            "vs_serial": round(p["gbps"] / gbps, 4) if gbps else 0,
        }
        if "hbm_peak_bytes" in p:
            # per-depth HBM high-water mark (ISSUE 13): throughput vs
            # memory headroom in one place, per bench round
            out["pipelined"]["hbm_peak_bytes"] = p["hbm_peak_bytes"]
        if "device_cache" in p:
            out["pipelined"]["device_cache"] = p["device_cache"]
    elif "pipeline_error" in result:
        out["pipeline_error"] = result["pipeline_error"]
    # fused metric (ISSUE 18): aggregated end-to-end throughput with
    # super-launch fusion armed under a multi-submitter backlog, plus
    # the fusion witnesses (fused_launches >= 1, launches < windows
    # dispatched) the perf smoke gate asserts on the same machinery
    if "fused" in result:
        f = result["fused"]
        out["fused"] = {
            "metric": "rs_8_3_encode_GBps_per_chip_fused",
            "value": round(f["gbps"], 3),
            "unit": "GB/s",
            "threads": f["threads"],
            "launches": f["launches"],
            "fused_launches": f["fused_launches"],
            "fused_windows": f["fused_windows"],
            "windows_dispatched": f["windows_dispatched"],
        }
        p = result.get("pipeline")
        if p and p.get("gbps"):
            out["fused"]["vs_pipelined"] = round(f["gbps"] / p["gbps"], 4)
    elif "fused_error" in result:
        out["fused_error"] = result["fused_error"]
    # padding-waste metric (ISSUE 18, lower-is-better): the bucketed
    # learner's steady-state pad fraction on a mixed-size workload,
    # next to the analytic pow2 baseline the same sizes would pay
    # without it
    if "pad_waste" in result:
        w = result["pad_waste"]
        out["pad_waste"] = {
            "metric": "padding_waste_ratio",
            "value": w["ratio"],
            "pow2_baseline": w["pow2_baseline"],
            "sizes": w["sizes"],
        }
    elif "pad_waste_error" in result:
        out["pad_waste_error"] = result["pad_waste_error"]
    # write-path offload metrics (ISSUE 20, same {metric, value} sub-
    # object shape): device crc32c GB/s and the fused compress+csum
    # write-path GB/s, both probe-checked byte-identical to their host
    # oracles before timing
    if "csum" in result:
        c = result["csum"]
        out["csum"] = {
            "metric": "bluestore_csum_GBps_per_chip",
            "value": round(c["gbps"], 3),
            "unit": "GB/s",
            "blocks": c["blocks"],
            "block_bytes": c["block_bytes"],
        }
    elif "csum_error" in result:
        out["csum_error"] = result["csum_error"]
    if "offload" in result:
        off = result["offload"]
        out["offload"] = {
            "metric": "write_path_offload_GBps",
            "value": round(off["gbps"], 3),
            "unit": "GB/s",
            "blocks": off["blocks"],
            "block_bytes": off["block_bytes"],
        }
    elif "offload_error" in result:
        out["offload_error"] = result["offload_error"]
    # multichip stage (ISSUE 6): aggregate GB/s of the mesh-sharded
    # launch path, alongside (never replacing) the per-chip metrics
    if "multichip" in result:
        m = result["multichip"]
        mc_out = {"devices": m.get("devices", 0)}
        if "encode_gbps" in m:
            mc_out["metric"] = "rs_8_3_encode_GBps_aggregate"
            mc_out["value"] = round(m["encode_gbps"], 3)
            mc_out["unit"] = "GB/s"
            mc_out["vs_per_chip"] = (
                round(m["encode_gbps"] / gbps, 4) if gbps else 0
            )
        if "decode_gbps" in m:
            mc_out["decode"] = {
                "metric": "rs_8_3_decode_GBps_aggregate",
                "value": round(m["decode_gbps"], 3),
                "unit": "GB/s",
            }
        for key in ("skipped", "error", "decode_error", "batch"):
            if key in m:
                mc_out[key] = m[key]
        out["multichip"] = mc_out
    if "stages" in result:
        out["stages"] = result["stages"]
    if "probe_s" in result:
        out["probe_s"] = result["probe_s"]
    # whether PR 4's backend-init retry fired this round (ISSUE 11
    # satellite): the next TPU round proves the round-4/5 hang fix by
    # showing either zero retries with a TPU platform, or a retry that
    # SALVAGED the TPU measurement instead of losing the round to CPU
    out["tpu_init_retries"] = init_retries
    if tpu_failure is not None:
        # machine-diffable failure taxonomy (replaces the free-text
        # tpu_error field): cause in {import_hang, backend_init_hang,
        # stage_hang, device_error} + stage/rc/detail evidence
        out["tpu_failure"] = tpu_failure
    if "flight" in result:
        # flight-recorder summary from the measuring child (ISSUE 8):
        # launch count, mean queue-wait, occupancy — the bench
        # trajectory tracks device utilization alongside GB/s
        out["flight"] = result["flight"]
    if "trace" in result:
        out["trace"] = result["trace"]
    # chaos-harness metrics (tools/chaos.py --out): fold chaos_p99_ms +
    # recovery_occupancy into the bench line so the PROGRESS trajectory
    # tracks them alongside GB/s (ROADMAP item 4)
    chaos_path = os.environ.get("BENCH_CHAOS_JSON", "")
    if chaos_path and os.path.exists(chaos_path):
        try:
            with open(chaos_path) as f:
                chaos = json.load(f)
            out["chaos"] = {
                k: chaos[k]
                for k in (
                    "chaos_p99_ms", "recovery_occupancy", "converged",
                    # ISSUE 10 workload-attribution keys: the SLO burn
                    # rate under mixed load, per-pool windowed p99, and
                    # the trace-sampling verdicts (budget adherence)
                    "slo_worst_burn_rate", "pool_p99_ms", "trace_sampling",
                )
                if k in chaos
            }
        except (OSError, json.JSONDecodeError) as e:
            _log(f"ignoring unreadable BENCH_CHAOS_JSON: {e!r}")
    # round-over-round trajectory gating (ISSUE 14): judge this round
    # against the trailing committed BENCH_r*.json rounds (same-platform
    # best — a next TPU round is automatically held to round 3's
    # 23.4 GB/s instead of silently resetting the story) and fold the
    # machine-readable regressions slice computed by
    # ceph_tpu/tools/perf_compare.py.  Guarded: the headline must
    # survive a compare fault, but the fault stays machine-visible.
    try:
        from ceph_tpu.tools.perf_compare import compare_round

        out["regressions"] = compare_round(
            out, os.path.dirname(os.path.abspath(__file__))
        )
    except Exception as e:
        _log(f"perf-compare fold failed: {e!r}")
        out["regressions"] = {"error": repr(e)}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
