"""On-chip byte-parity tier: the production kernels vs the host GF oracle.

Round-4 verdict weak item 5: the only hardware byte check was bench.py's
preflight on the RS(8,3) encode geometry; decode-matrix kernels, the
smaller tile geometries, CLAY/LRC paths, and the sharded entry point had
never run on a real chip.  Each test here is deliberately tiny (a few
stripes) — the cost is one remote compile per kernel shape, not data.

Reference pattern: the exhaustive-erasure loop of
/root/reference/src/test/erasure-code/TestErasureCodeIsa.cc:51-90 (encode,
erase every combination, decode, byte-compare).
"""

import numpy as np
import pytest

from ceph_tpu.codec.registry import instance
from ceph_tpu.gf import gf_matmul, isa_rs_vandermonde_matrix
from ceph_tpu.ops.pallas_gf import pick_geometry

RNG = np.random.default_rng(0xC3F)


def _oracle_parity(ec, data):
    """Host-side GF parity for a (S, k, L) batch via the codec's matrix."""
    mat = np.asarray(ec.distribution_matrix())[ec.k :]
    return np.stack([gf_matmul(mat, data[s]) for s in range(data.shape[0])])


@pytest.mark.parametrize(
    "L,geom",
    [
        (128 * 1024, (128, 256)),  # full-size lane tiles (the bench shape)
        (512, (4, 128)),
        (256, (4, 64)),
        (128, (4, 32)),
    ],
)
def test_swar_encode_every_geometry(tpu, L, geom):
    """The SWAR kernel non-interpret at every tile geometry in
    pallas_gf pick_geometry (cols 256/128/64/32)."""
    assert pick_geometry(L) == geom
    k, m = 8, 3
    ec = instance().factory("tpu", {"k": str(k), "m": str(m)})
    data = RNG.integers(0, 256, (2, k, L), dtype=np.uint8)
    got = np.asarray(ec.encode_array(data))
    assert np.array_equal(got, _oracle_parity(ec, data))


def test_decode_matrices_from_lru(tpu):
    """Decode-matrix kernels (signature-keyed LRU) on-chip for every
    single- and double-erasure pattern class of RS(8,3)."""
    k, m = 8, 3
    ec = instance().factory("tpu", {"k": str(k), "m": str(m)})
    L = 512
    data = RNG.integers(0, 256, (2, k, L), dtype=np.uint8)
    parity = _oracle_parity(ec, data)
    full = np.concatenate([data, parity], axis=1)
    # data-only, parity-only, and mixed erasures (distinct decode matrices)
    for erasures in ([0], [9], [0, 1], [0, 9], [9, 10], [0, 5, 10]):
        idx = ec.decode_index(erasures)
        rebuilt = np.asarray(ec.decode_array(erasures, full[:, idx, :]))
        assert np.array_equal(rebuilt, full[:, erasures, :]), erasures


def test_clay_subchunk_repair(tpu):
    """CLAY coupling transforms on-chip: single-shard repair reads q^t
    sub-chunks and reconstructs bit-exactly."""
    ec = instance().factory("clay", {"k": "4", "m": "2"})
    size = ec.get_chunk_size(4 * 8192) * 4
    data = RNG.integers(0, 256, size, dtype=np.uint8).tobytes()
    chunks = ec.encode(set(range(6)), data)
    lost = 2
    have = {i: v for i, v in chunks.items() if i != lost}
    out = ec.decode({lost}, have, chunk_size=len(chunks[lost]))
    assert np.array_equal(
        np.asarray(out[lost]), np.asarray(chunks[lost])
    )


def test_lrc_local_repair(tpu):
    """LRC layered decode on-chip: a single failure repairs from its
    locality group."""
    ec = instance().factory("lrc", {"k": "4", "m": "2", "l": "3"})
    n = ec.get_chunk_count()
    size = ec.get_chunk_size(4 * 4096) * 4
    data = RNG.integers(0, 256, size, dtype=np.uint8).tobytes()
    chunks = ec.encode(set(range(n)), data)
    have = {i: v for i, v in chunks.items() if i != 1}
    out = ec.decode({1}, have, chunk_size=len(chunks[1]))
    assert np.array_equal(np.asarray(out[1]), np.asarray(chunks[1]))


def test_shardmap_1dev_plan_encode(tpu):
    """The production sharded entry point (shard_map of the Pallas plan)
    compiles and runs on hardware with a 1-device mesh — the minimum
    hardware proof of the multi-chip path (VERDICT r4 weak item 6)."""
    import jax
    from jax.sharding import Mesh

    from ceph_tpu.ops.pallas_gf import CodingPlan
    from ceph_tpu.parallel.sharded import sharded_plan_encode

    k, m = 8, 3
    mat = isa_rs_vandermonde_matrix(k, m)[k:]
    plan = CodingPlan(mat)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1), ("pod", "stripe", "lane"))
    data = RNG.integers(0, 256, (4, k, 512), dtype=np.uint8)
    out = np.asarray(sharded_plan_encode(plan, jax.numpy.asarray(data), mesh))
    for s in range(4):
        assert np.array_equal(out[s], gf_matmul(mat, data[s]))
