"""On-TPU correctness tier harness (VERDICT r4 item 3).

Unlike tests/conftest.py (which forces a virtual CPU mesh and pops the
axon gate variable), this tier runs against the REAL chip: the Pallas
SWAR kernel non-interpret for every tile geometry, decode matrices from
the signature LRU, CLAY coupling transforms, and a 1-device shard_map of
the production sharded entry point — bytes compared against the host GF
oracle (the exhaustive-erasure gtest pattern,
/root/reference/src/test/erasure-code/TestErasureCodeIsa.cc:51-90).

Gating: the whole tier SKIPS unless ONCHIP=1 is exported, because merely
importing jax with the axon gate variable set hangs every process while
the tunnel is wedged.  The recovery runner (benchmarks/diag/
tpu_autorun_r5.sh) sets ONCHIP=1 once the tunnel answers a probe.
"""

import os

import pytest


def pytest_ignore_collect(collection_path, config):
    # Gate BEFORE collection: merely importing a test module here pulls in
    # jax (via ceph_tpu.ops), and with the axon gate variable set a wedged
    # tunnel hangs that import forever — a skip marker added after
    # collection would never run.
    if os.environ.get("ONCHIP") != "1":
        return True
    return None


@pytest.fixture(scope="session")
def tpu():
    """The real TPU device, or skip when the backend resolves elsewhere."""
    import jax

    devs = jax.devices()
    if devs[0].platform != "tpu":
        pytest.skip(f"backend is {devs[0].platform}, not tpu")
    return devs[0]
