#!/bin/bash
# Round-5 TPU recovery runner.  The axon tunnel has been wedged since
# round 4 (~05:15 UTC; /tmp/tpu_probe.log has 147+ failed probes).  This
# loop probes gently (one small client every 3 min) and, the moment the
# tunnel answers, produces every TPU artifact of the round in order of
# value:
#   1. bench.py                    -> /tmp/bench_tpu_r5.json (headline GB/s)
#   2. five-config BASELINE sweep  -> benchmarks/BASELINE_SWEEP_tpu_r5.jsonl
#   3. on-chip correctness tier    -> /tmp/onchip_tier_r5.log (pytest tests_tpu)
# Probe rc is checked DIRECTLY on the timeout command (the round-5 probe
# bug: `rc=$?` after a pipeline reads tail's status, always 0).
cd /root/repo || exit 1
LOG=/tmp/tpu_autorun_r5.log
for i in $(seq 1 400); do
  if timeout 120 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu'; import jax.numpy as jnp; assert int(jnp.arange(4).sum())==6" 2>/dev/null; then
    echo "$(date -u +%H:%M:%S) TPU RECOVERED (try $i)" >> "$LOG"
    echo "$(date -u +%H:%M:%S) bench.py" >> "$LOG"
    timeout 900 python bench.py > /tmp/bench_tpu_r5.json 2>/tmp/bench_tpu_r5.log
    echo "$(date -u +%H:%M:%S) bench rc=$? $(cat /tmp/bench_tpu_r5.json)" >> "$LOG"
    if grep -q '"platform": "tpu"' /tmp/bench_tpu_r5.json 2>/dev/null; then
      cp /tmp/bench_tpu_r5.json benchmarks/diag/BENCH_tpu_r5_auto.json
    fi
    echo "$(date -u +%H:%M:%S) baseline sweep" >> "$LOG"
    rm -f benchmarks/BASELINE_SWEEP_tpu_r5.jsonl
    timeout 2400 python -m ceph_tpu.tools.bench_sweep --baseline --iterations 8 \
      --out benchmarks/BASELINE_SWEEP_tpu_r5.jsonl > /tmp/sweep_tpu_r5.log 2>&1
    echo "$(date -u +%H:%M:%S) sweep rc=$? lines=$(wc -l < benchmarks/BASELINE_SWEEP_tpu_r5.jsonl 2>/dev/null)" >> "$LOG"
    echo "$(date -u +%H:%M:%S) on-chip tier" >> "$LOG"
    ONCHIP=1 timeout 1800 python -m pytest tests_tpu/ -v > /tmp/onchip_tier_r5.log 2>&1
    echo "$(date -u +%H:%M:%S) tier rc=$? $(tail -1 /tmp/onchip_tier_r5.log)" >> "$LOG"
    echo "$(date -u +%H:%M:%S) ALL DONE" >> "$LOG"
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) try $i: still wedged" >> "$LOG"
  sleep 180
done
