"""Kernel experiment round 3: SWAR XOR-schedule with in-kernel pltpu.bitcast.

The exp2 SWAR variant died on XLA's uint8<->int32 marshalling (5.9 ms just
for the bitcast round trip: lane-consecutive packing is a slow relayout).
Here the kernel takes plain uint8 blocks and reinterprets them in VMEM with
pltpu.bitcast along the SUBLANE axis -- on TPU a (4R, C) uint8 tile already
stores 4 sublanes packed per 32-bit register row, so the bitcast is a free
register reinterpret.  The byte->word grouping this induces (bytes strided
by the lane count) is fine: the GF(2^8) transform is byte-elementwise, so
any consistent grouping of bytes into words is valid as long as the output
is bitcast back the same way.

Usage: python benchmarks/diag/kern_exp3.py [filter ...]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from ceph_tpu.gf import gf_matmul, isa_rs_vandermonde_matrix
from ceph_tpu.ops.pallas_gf import CodingPlan
from kern_exp2 import schedule_from_matrix

K, M = 8, 3
CHUNK = 128 * 1024
BATCH = 64
ITERS = 30
MASK = 0x01010101


def _kernel_swar3(data_ref, out_ref, *, sched, m: int):
    """data_ref (1, k, R, C) uint8; out_ref (1, m, R, C) uint8; R % 4 == 0."""
    k = data_ref.shape[1]
    planes = {}
    for j in range(k):
        d32 = pltpu.bitcast(data_ref[0, j], jnp.int32)  # (R/4, C)
        for b in range(8):
            planes[(j, b)] = (
                jax.lax.shift_right_logical(d32, b) if b else d32
            ) & MASK
    for i in range(m):
        word = None
        for r in range(8):
            row = sched[i * 8 + r]
            acc = planes[row[0]]
            for t in row[1:]:
                acc = acc ^ planes[t]
            contrib = acc << r if r else acc
            word = contrib if word is None else word | contrib
        out_ref[0, i] = pltpu.bitcast(word, jnp.uint8)


def make_swar3(gfm: np.ndarray, rows: int, cols: int):
    """fn: (S, k, L) uint8 -> (S, m, L) uint8.  Block = (rows, cols) bytes."""
    m, k = gfm.shape
    sched = schedule_from_matrix(gfm)

    @jax.jit
    def run(data):
        s, kk, L = data.shape
        tile = rows * cols
        assert L % tile == 0, (L, tile)
        nt = L // tile
        d = data.reshape(s, kk, nt, rows, cols)
        grid = (s, nt)
        out = pl.pallas_call(
            functools.partial(_kernel_swar3, sched=sched, m=m),
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, kk, 1, rows, cols),
                    lambda i, j: (i, 0, j, 0, 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (1, m, 1, rows, cols),
                lambda i, j: (i, 0, j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((s, m, nt, rows, cols), jnp.uint8),
        )(d)
        return out.reshape(s, m, L)

    return run


def measure(fn, data, label, in_bytes):
    out = fn(data)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(data)
    jax.block_until_ready(out)
    el = time.perf_counter() - t0
    gbps = in_bytes * ITERS / el / 1e9
    print(f"{label:28s} {gbps:8.2f} GB/s  ({el/ITERS*1e3:.2f} ms/iter)", flush=True)
    return gbps


def main():
    want = sys.argv[1:] or None
    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev.device_kind})", flush=True)
    gfm = isa_rs_vandermonde_matrix(K, M)[K:]
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (BATCH, K, CHUNK), dtype=np.uint8))
    in_bytes = BATCH * K * CHUNK

    probe = np.asarray(data[:4, :, :32768])
    oracle = np.stack([gf_matmul(gfm, probe[s]) for s in range(probe.shape[0])])

    def check(fn):
        got = np.asarray(fn(jnp.asarray(probe)))
        assert np.array_equal(got, oracle), "parity mismatch"

    variants = {"cur_plan": lambda: CodingPlan(gfm)}
    for rows, cols in ((8, 512), (16, 256), (16, 512), (32, 128), (32, 256), (32, 512), (64, 512), (128, 256)):
        variants[f"swar3_r{rows}_c{cols}"] = functools.partial(make_swar3, gfm, rows, cols)

    for name, mk in variants.items():
        if want and not any(w in name for w in want):
            continue
        try:
            fn = mk()
            check(fn)
            measure(fn, data, name, in_bytes)
        except Exception as e:
            print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:160]}", flush=True)


if __name__ == "__main__":
    main()
