"""Kernel experiment round 4: separate launch overhead from kernel compute.

Across exp1-3 the SAME kernel swings 8-21 GB/s between processes, and every
variant lands in 3-6 ms/iter regardless of content -- smells like per-launch
overhead (axon = tunneled TPU) rather than compute.  Probes:
  - copy-only pallas kernel (the floor: HBM read+write, no math)
  - batch scaling 16/64/256 MB per launch for copy, cur, swar3
  - repeated interleaved measurement for variance
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from ceph_tpu.gf import isa_rs_vandermonde_matrix
from ceph_tpu.ops.pallas_gf import CodingPlan
from kern_exp3 import make_swar3

K, M = 8, 3
CHUNK = 128 * 1024
ITERS = 30


def _copy_kernel(data_ref, out_ref):
    out_ref[0] = data_ref[0, :3]


def make_copy(tile: int):
    @jax.jit
    def run(data):
        s, k, L = data.shape
        grid = (s, L // tile)
        return pl.pallas_call(
            _copy_kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((1, 3, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((s, 3, L), jnp.uint8),
        )(data)

    return run


def measure(fn, data, label, reps=3):
    in_bytes = data.shape[0] * data.shape[1] * data.shape[2]
    out = fn(data)
    jax.block_until_ready(out)
    res = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = fn(data)
        jax.block_until_ready(out)
        el = time.perf_counter() - t0
        res.append(in_bytes * ITERS / el / 1e9)
    msiter = in_bytes * ITERS / max(res) / 1e9 and (in_bytes / max(res) / 1e6)
    print(
        f"{label:24s} " + " ".join(f"{g:7.2f}" for g in res) + f" GB/s (best {max(res):.1f}, {msiter:.2f} ms/iter)",
        flush=True,
    )
    return max(res)


def main():
    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev.device_kind})", flush=True)
    gfm = isa_rs_vandermonde_matrix(K, M)[K:]
    rng = np.random.default_rng(0)

    copy = make_copy(4096)
    cur = CodingPlan(gfm)
    swar = make_swar3(gfm, 128, 256)

    for batch in (16, 64, 256):
        data = jnp.asarray(rng.integers(0, 256, (batch, K, CHUNK), dtype=np.uint8))
        print(f"--- batch={batch} ({batch * K * CHUNK // 2**20} MiB/launch)", flush=True)
        measure(copy, data, f"copy b{batch}")
        measure(cur, data, f"cur b{batch}")
        measure(swar, data, f"swar3_r128_c256 b{batch}")
        del data


if __name__ == "__main__":
    main()
