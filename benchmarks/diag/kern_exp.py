"""Kernel-variant experiments for the Pallas GF coding kernel (run on TPU).

Measures GB/s (input bytes / elapsed) for several kernel formulations to
locate the bottleneck between MXU utilization (the (8m, 8k) matmul is tiny
vs the 128x128 array) and VPU work (bit-plane expansion + mod-2 fold).

Usage:  python benchmarks/diag/kern_exp.py [variant ...]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from ceph_tpu.gf import isa_rs_vandermonde_matrix
from ceph_tpu.ops.pallas_gf import CodingPlan
from ceph_tpu.gf.bitslice import expand_matrix


def arrange_dense_matrix(gfm):
    """(m, k) GF matrix -> dense (8m, 8k) matmul layout (the retired
    MXU formulation this experiment measured)."""
    import numpy as _np
    gfm = _np.asarray(gfm, dtype=_np.uint8)
    m, k = gfm.shape
    plain = expand_matrix(gfm)
    perm = _np.array([j * 8 + b for b in range(8) for j in range(k)])
    return plain[:, perm].astype(_np.float32)

K, M = 8, 3
CHUNK = 128 * 1024
BATCH = 64
ITERS = 30


def block_diag(bm: np.ndarray, g: int) -> np.ndarray:
    r, c = bm.shape
    out = np.zeros((r * g, c * g), dtype=bm.dtype)
    for i in range(g):
        out[i * r : (i + 1) * r, i * c : (i + 1) * c] = bm
    return out


def _kernel_grouped(bm_ref, data_ref, out_ref, *, k: int, m: int, g: int):
    """g stripes per program: block-diag (8mg, 8kg) matmul."""
    pieces = []
    for s in range(g):
        d32 = data_ref[s].astype(jnp.int32)  # (k, T)
        for b in range(8):
            pieces.append((d32 >> b) & 1)
    planes = jnp.concatenate(pieces, axis=0)  # (8kg, T)
    cd = bm_ref.dtype
    acc = jax.lax.dot_general(
        bm_ref[:],
        planes.astype(cd),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32 if cd == jnp.int8 else jnp.float32,
    )  # (8mg, T)
    bits = acc.astype(jnp.int32) & 1
    t = bits.shape[-1]
    grouped = bits.reshape(g, m, 8, t)
    weights = (jnp.int32(1) << jnp.arange(8, dtype=jnp.int32)).reshape(1, 1, 8, 1)
    out_ref[...] = (grouped * weights).sum(axis=2).astype(jnp.uint8)


def make_grouped(gfm: np.ndarray, g: int, dtype, tile: int):
    m, k = gfm.shape
    bm = block_diag(arrange_dense_matrix(gfm), g)
    bmj = jnp.asarray(bm, dtype=dtype)

    @jax.jit
    def run(data):  # (S, k, L) uint8
        s, kk, L = data.shape
        grid = (s // g, L // tile)
        return pl.pallas_call(
            functools.partial(_kernel_grouped, k=k, m=m, g=g),
            grid=grid,
            in_specs=[
                pl.BlockSpec(bm.shape, lambda i, j: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((g, k, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((g, m, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((s, m, L), jnp.uint8),
        )(bmj, data)

    return run


def _kernel_mm_only(bm_ref, planes_ref, out_ref):
    """Matmul ceiling probe: planes pre-expanded on host, bf16 in HBM."""
    acc = jax.lax.dot_general(
        bm_ref[:], planes_ref[0],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    out_ref[0] = acc.astype(jnp.int32).astype(jnp.uint8)


def make_mm_only(gfm: np.ndarray, tile: int):
    bm = arrange_dense_matrix(gfm)
    bmj = jnp.asarray(bm, dtype=jnp.bfloat16)
    mm8 = bm.shape[0]

    @jax.jit
    def run(planes):  # (S, 8k, L) bf16
        s, kk8, L = planes.shape
        grid = (s, L // tile)
        return pl.pallas_call(
            _kernel_mm_only,
            grid=grid,
            in_specs=[
                pl.BlockSpec(bm.shape, lambda i, j: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, kk8, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, mm8, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((s, mm8, L), jnp.uint8),
        )(bmj, planes)

    return run


def _kernel_expand_only(data_ref, out_ref):
    d32 = data_ref[0].astype(jnp.int32)
    planes = jnp.concatenate([(d32 >> b) & 1 for b in range(8)], axis=0)
    out_ref[0] = planes.sum(axis=0, keepdims=True).astype(jnp.uint8)[:1]


def make_expand_only(tile: int):
    @jax.jit
    def run(data):
        s, k, L = data.shape
        grid = (s, L // tile)
        return pl.pallas_call(
            _kernel_expand_only,
            grid=grid,
            in_specs=[pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((1, 1, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((s, 1, L), jnp.uint8),
        )(data)

    return run


def measure(fn, data, label, in_bytes):
    out = fn(data)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(data)
    jax.block_until_ready(out)
    el = time.perf_counter() - t0
    gbps = in_bytes * ITERS / el / 1e9
    print(f"{label:28s} {gbps:8.2f} GB/s  ({el/ITERS*1e3:.2f} ms/iter)", flush=True)
    return gbps


def main():
    want = sys.argv[1:] or None
    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev.device_kind})", flush=True)
    gfm = isa_rs_vandermonde_matrix(K, M)[K:]
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (BATCH, K, CHUNK), dtype=np.uint8))
    in_bytes = BATCH * K * CHUNK

    oracle = None

    def check(fn):
        nonlocal oracle
        if oracle is None:
            from ceph_tpu.gf import gf_matmul
            small = np.asarray(data[:2, :, :1024])
            oracle = np.stack([gf_matmul(gfm, small[s]) for s in range(2)])
        got = np.asarray(fn(data[:2, :, :1024]))
        assert np.array_equal(got, oracle), "parity mismatch"

    variants = {}
    variants["cur_plan"] = lambda: CodingPlan(gfm)
    for g in (2, 4, 8):
        for dt, dn in ((jnp.bfloat16, "bf16"), (jnp.int8, "int8")):
            for tile in (2048, 4096):
                variants[f"g{g}_{dn}_t{tile}"] = functools.partial(
                    make_grouped, gfm, g, dt, tile
                )
    variants["g1_int8_t4096"] = functools.partial(make_grouped, gfm, 1, jnp.int8, 4096)

    for name, mk in variants.items():
        if want and not any(w in name for w in want):
            continue
        try:
            fn = mk()
            check(fn)
            measure(fn, data, name, in_bytes)
        except Exception as e:
            print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:120]}", flush=True)

    if not want or "mm" in want:
        # matmul-only ceiling (planes pre-expanded, so 8x the HBM read traffic
        # in bf16 -> 16x bytes; still shows the MXU-side ceiling per column)
        planes = jnp.concatenate(
            [((data.astype(jnp.int32) >> b) & 1) for b in range(8)], axis=1
        ).astype(jnp.bfloat16)
        fn = make_mm_only(gfm, 2048)
        measure(fn, planes, "mm_only(bf16 planes)", in_bytes)
    if not want or "expand" in want:
        fn = make_expand_only(4096)
        measure(fn, data, "expand_only", in_bytes)


if __name__ == "__main__":
    main()
