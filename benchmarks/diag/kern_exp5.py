"""Kernel experiment round 5: serial-chain methodology (bench.py's) applied
to the kernel variants.  Round-4 showed that independent repeated launches
overlap/elide on the axon backend (18 TB/s "copy"), so every measurement here
chains launch n+1's input on launch n's output with buffer donation, exactly
like bench.py.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

from ceph_tpu.gf import isa_rs_vandermonde_matrix
from ceph_tpu.ops.pallas_gf import CodingPlan
from kern_exp3 import make_swar3
from kern_exp4 import make_copy

K, M = 8, 3
CHUNK = 128 * 1024
ITERS = 30


def measure_chained(fn, data, label, reps=3):
    in_bytes = data.shape[0] * data.shape[1] * data.shape[2]

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(d, p):
        patch = (p[:1, :1, :128] ^ jnp.uint8(1)).reshape(1, 1, 128)
        d2 = jax.lax.dynamic_update_slice(d, patch, (0, 0, 0))
        return d2, fn(d2)

    p = fn(data)
    data, p = step(data, p)
    jax.block_until_ready((data, p))
    res = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            data, p = step(data, p)
        jax.block_until_ready((data, p))
        el = time.perf_counter() - t0
        res.append(in_bytes * ITERS / el / 1e9)
    print(
        f"{label:24s} " + " ".join(f"{g:7.2f}" for g in res)
        + f" GB/s (best {max(res):.1f}, {in_bytes / max(res) / 1e6:.3f} ms/iter)",
        flush=True,
    )
    return max(res)


def main():
    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev.device_kind})", flush=True)
    gfm = isa_rs_vandermonde_matrix(K, M)[K:]
    rng = np.random.default_rng(0)

    variants = {
        "copy_t4096": make_copy(4096),
        "cur_plan": CodingPlan(gfm),
        "swar3_r128_c256": make_swar3(gfm, 128, 256),
        "swar3_r32_c128": make_swar3(gfm, 32, 128),
        "swar3_r512_c256": make_swar3(gfm, 512, 256),
    }
    for batch in (64, 256):
        print(f"--- batch={batch} ({batch * K * CHUNK // 2**20} MiB/launch)", flush=True)
        for name, fn in variants.items():
            data = jnp.asarray(rng.integers(0, 256, (batch, K, CHUNK), dtype=np.uint8))
            try:
                measure_chained(fn, data, f"{name} b{batch}")
            except Exception as e:
                print(f"{name:24s} FAILED: {type(e).__name__}: {str(e)[:140]}", flush=True)
            del data


if __name__ == "__main__":
    main()
