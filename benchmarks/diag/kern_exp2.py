"""Kernel experiment round 2: SWAR XOR-schedule formulation vs matmul.

Hypothesis from round 1 (kern_exp.py): expand_only (uint8 cast + 8x shift/and
per byte) alone runs at ~22 GB/s -- the VPU expansion is the bottleneck, not
the MXU matmul.  A SWAR formulation on int32 words (4 bytes/elem) does the
plane extraction with 4x fewer vector elems and no uint8 relayouts, then
computes output bit-planes as a compile-time XOR schedule (GF(2) linearity
keeps the 4 packed byte fields independent), assembling output bytes with
shift+or.  No MXU, no bf16 casts, no uint8 in the kernel.

Usage: python benchmarks/diag/kern_exp2.py [filter ...]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from ceph_tpu.gf import gf_matmul, isa_rs_vandermonde_matrix
from ceph_tpu.gf.bitslice import expand_matrix
from ceph_tpu.ops.pallas_gf import CodingPlan

K, M = 8, 3
CHUNK = 128 * 1024
BATCH = 64
ITERS = 30
MASK = 0x01010101


def schedule_from_matrix(gfm: np.ndarray):
    """(m, k) GF matrix -> per-output-bit-row list of (j, b) term pairs."""
    plain = expand_matrix(np.asarray(gfm, dtype=np.uint8))  # (8m, 8k)
    m8, k8 = plain.shape
    return [
        [(c // 8, c % 8) for c in range(k8) if plain[o, c]] for o in range(m8)
    ]


def _kernel_swar(data_ref, out_ref, *, sched, m: int):
    """data_ref (1, k, 8, WT) int32; out_ref (1, m, 8, WT) int32."""
    needed = sorted({t for row in sched for t in row})
    planes = {}
    for (j, b) in needed:
        d = data_ref[0, j]  # (8, WT)
        planes[(j, b)] = (
            jax.lax.shift_right_logical(d, b) if b else d
        ) & MASK
    for i in range(m):
        word = None
        for r in range(8):
            row = sched[i * 8 + r]
            acc = planes[row[0]]
            for t in row[1:]:
                acc = acc ^ planes[t]
            contrib = acc << r if r else acc
            word = contrib if word is None else word | contrib
        out_ref[0, i] = word


def make_swar(gfm: np.ndarray, wt: int):
    """Returns fn: (S, k, L) uint8 -> (S, m, L) uint8 via SWAR kernel."""
    m, k = gfm.shape
    sched = schedule_from_matrix(gfm)

    @jax.jit
    def run(data):
        s, kk, L = data.shape
        W = L // 4
        w8 = W // 8
        d32 = jax.lax.bitcast_convert_type(
            data.reshape(s, kk, 8, w8, 4), jnp.int32
        )  # (s, k, 8, w8)
        grid = (s, w8 // wt)
        out32 = pl.pallas_call(
            functools.partial(_kernel_swar, sched=sched, m=m),
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, kk, 8, wt), lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
                )
            ],
            out_specs=pl.BlockSpec(
                (1, m, 8, wt), lambda i, j: (i, 0, 0, j), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct((s, m, 8, w8), jnp.int32),
        )(d32)
        return jax.lax.bitcast_convert_type(out32, jnp.uint8).reshape(s, m, L)

    return run


def make_bitcast_only():
    """Cost of the uint8 <-> int32 view + reshape round trip alone."""

    @jax.jit
    def run(data):
        s, k, L = data.shape
        w8 = L // 32
        d32 = jax.lax.bitcast_convert_type(data.reshape(s, k, 8, w8, 4), jnp.int32)
        return jax.lax.bitcast_convert_type(d32, jnp.uint8).reshape(s, k, L)[:, :3]

    return run


def measure(fn, data, label, in_bytes):
    out = fn(data)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(data)
    jax.block_until_ready(out)
    el = time.perf_counter() - t0
    gbps = in_bytes * ITERS / el / 1e9
    print(f"{label:28s} {gbps:8.2f} GB/s  ({el/ITERS*1e3:.2f} ms/iter)", flush=True)
    return gbps


def main():
    want = sys.argv[1:] or None
    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev.device_kind})", flush=True)
    gfm = isa_rs_vandermonde_matrix(K, M)[K:]
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (BATCH, K, CHUNK), dtype=np.uint8))
    in_bytes = BATCH * K * CHUNK

    probe = np.asarray(data[:8, :, :16384])
    oracle = np.stack([gf_matmul(gfm, probe[s]) for s in range(probe.shape[0])])

    def check(fn):
        got = np.asarray(fn(jnp.asarray(probe)))
        assert np.array_equal(got, oracle), "parity mismatch"

    variants = {}
    variants["cur_plan"] = lambda: CodingPlan(gfm)
    for wt in (128, 256, 512, 1024):
        variants[f"swar_wt{wt}"] = functools.partial(make_swar, gfm, wt)

    for name, mk in variants.items():
        if want and not any(w in name for w in want):
            continue
        try:
            fn = mk()
            check(fn)
            measure(fn, data, name, in_bytes)
        except Exception as e:
            print(f"{name:28s} FAILED: {type(e).__name__}: {str(e)[:160]}", flush=True)

    if not want or any("bitcast" in w for w in want):
        try:
            fn = make_bitcast_only()
            measure(fn, data, "bitcast_roundtrip_only", in_bytes)
        except Exception as e:
            print(f"bitcast_only FAILED: {type(e).__name__}: {str(e)[:160]}", flush=True)


if __name__ == "__main__":
    main()
