"""Long-deadline axon backend-init probe: does jax.devices() EVER return?
Logs progress with timestamps; dumps all-thread stacks every 120s."""
import faulthandler, sys, time, threading

LOG = "/root/repo/benchmarks/diag/tpu_probe.log"
f = open(LOG, "a", buffering=1)
def log(m): f.write(f"{time.strftime('%H:%M:%S')} +{time.time()-T0:8.1f}s {m}\n")
T0 = time.time()
log("=== probe start ===")
faulthandler.dump_traceback_later(120, repeat=True, file=f)
import jax
log(f"jax {jax.__version__} imported")
try:
    devs = jax.devices()
    log(f"SUCCESS devices={devs}")
    import numpy as np
    x = jax.numpy.ones((256, 256), dtype=jax.numpy.bfloat16)
    t1 = time.time()
    y = (x @ x).block_until_ready()
    log(f"matmul ok in {time.time()-t1:.1f}s result_sum={float(y.sum()):.1f} platform={devs[0].platform}")
except Exception as e:
    log(f"FAILED {type(e).__name__}: {e}")
log("=== probe end ===")
